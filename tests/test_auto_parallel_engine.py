"""Auto-parallel static Engine tests (VERDICT #6): dist.to_static + Engine
train a GPT fixture on the 8-device mesh; losses match the dygraph run.
Pattern: test/auto_parallel/ engine tests with the get_gpt_model fixture.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_parallel.static_engine import (
    choose_batch_axis,
    complete_annotations,
    estimate_cost,
)


def _make_data(n=32, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, 1)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    return X, Y


def _loader(X, Y, bsz):
    def gen():
        for i in range(0, len(X), bsz):
            yield [paddle.to_tensor(X[i:i + bsz]),
                   paddle.to_tensor(Y[i:i + bsz])]

    class L:
        def __iter__(self):
            return gen()

    return L()


def test_completion_pass_defaults_to_replicate():
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    paddle.framework.random.seed(0)
    m = nn.Linear(4, 4)
    ann = complete_annotations(m, mesh)
    assert len(ann) == 2
    for pls in ann.values():
        assert len(pls) == 2
        assert all(type(p).__name__ == "Replicate" for p in pls)


def test_cost_model_prefers_bigger_dp():
    mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["a", "b"])
    paddle.framework.random.seed(0)
    m = nn.Linear(64, 64)
    c4 = estimate_cost(m, mesh, "a", batch_size=32)
    c2 = estimate_cost(m, mesh, "b", batch_size=32)
    # compute dominates at this size: dp=4 is cheaper per device
    assert c4.flops_per_dev < c2.flops_per_dev
    assert choose_batch_axis(m, mesh, 32) in ("a", "b")


def test_dist_model_trains_and_matches_dygraph():
    X, Y = _make_data()
    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])

    def build():
        paddle.framework.random.seed(42)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
        return m, o

    # static engine run
    m1, o1 = build()
    dm = dist.to_static(m1, _loader(X, Y, 16), nn.MSELoss(), o1, mesh=mesh)
    dm.train()
    static_losses = []
    for xb, yb in _loader(X, Y, 16):
        static_losses.append(float(dm(xb, yb).numpy()))

    # dygraph run, same seed/data
    m2, o2 = build()
    lossfn = nn.MSELoss()
    dy_losses = []
    for xb, yb in _loader(X, Y, 16):
        loss = lossfn(m2(xb), yb)
        loss.backward()
        o2.step()
        o2.clear_grad()
        dy_losses.append(float(loss.numpy()))

    np.testing.assert_allclose(static_losses, dy_losses, rtol=2e-4,
                               atol=1e-6)
    # params end identical too
    for p, q in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), rtol=2e-4,
                                   atol=1e-5)


def test_engine_fit_evaluate_gpt_fixture():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    cfg = gpt_tiny(hidden_size=16, num_layers=2, num_heads=2, vocab_size=32,
                   max_position_embeddings=16)

    class CE(nn.Layer):
        def forward(self, logits, labels):
            import paddle_tpu.nn.functional as F
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1])).mean()

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (16, 8)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (16, 8)).astype(np.int32)

    def loader():
        class L:
            def __iter__(self):
                for i in range(0, 16, 8):
                    yield [paddle.to_tensor(ids[i:i + 8]),
                           paddle.to_tensor(labels[i:i + 8])]

        return L()

    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
    paddle.framework.random.seed(7)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    eng = dist.Engine(model, CE(), optimizer, mesh=mesh)
    history = eng.fit(loader(), epochs=3)
    assert len(history) == 6
    assert all(np.isfinite(history))
    assert history[-1] < history[0]  # training moves
    ev = eng.evaluate(loader())
    assert np.isfinite(ev["loss"])


def test_cross_mesh_reshard():
    """VERDICT r3 #7: reshard the SAME tensor across different
    ProcessMeshes — disjoint device sets and different topologies — with
    value preservation (the reference's reshard_funcs library capability;
    XLA device_put emits the transfers/collectives)."""
    import jax

    devs = jax.devices()
    m_a = dist.ProcessMesh(shape=[4], dim_names=["x"],
                           process_ids=[d.id for d in devs[:4]])
    m_b = dist.ProcessMesh(shape=[2, 2], dim_names=["p", "q"],
                           process_ids=[d.id for d in devs[4:8]])
    rng = np.random.default_rng(0)
    val = rng.normal(size=(8, 8)).astype(np.float32)
    t = dist.shard_tensor(paddle.to_tensor(val), m_a, [dist.Shard(0)])
    dev_a = {d.id for d in t._value.sharding.device_set}
    assert dev_a == {d.id for d in devs[:4]}

    # cross-mesh: different device set AND different topology/placements
    t2 = dist.reshard(t, m_b, [dist.Shard(0), dist.Shard(1)])
    np.testing.assert_allclose(np.asarray(t2._value), val)
    dev_b = {d.id for d in t2._value.sharding.device_set}
    assert dev_b == {d.id for d in devs[4:8]}
    assert dev_a.isdisjoint(dev_b)

    # back again with a placement change (Shard -> Replicate)
    t3 = dist.reshard(t2, m_a, [dist.Replicate()])
    np.testing.assert_allclose(np.asarray(t3._value), val)
    assert t3.process_mesh is m_a


def test_cost_model_chooses_tp_for_large_weights():
    from paddle_tpu.distributed.auto_parallel.static_engine import (
        choose_tp_placements,
    )

    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    paddle.framework.random.seed(1)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.big = nn.Linear(1024, 1024)   # 4 MB weight: shard
            self.small = nn.Linear(8, 8)       # tiny: keep replicated

        def forward(self, x):
            return self.small(self.big(x)[..., :8])

    net = Net()
    ann = choose_tp_placements(net, mesh, "mp", batch_size=8, seq_len=1)
    big_w = net.big.weight
    small_w = net.small.weight
    assert id(big_w) in ann, "large weight must shard over the tp axis"
    assert id(small_w) not in ann, "tiny weight must stay replicated"
    pls = ann[id(big_w)]
    assert isinstance(pls[1], dist.Shard) and pls[1].get_dim() == 1


def test_engine_pp_gpt_matches_dygraph():
    """VERDICT r3 #7 done-criterion: the GPT fixture trains through the
    Engine with a pp axis (schedule engine) on a pp x dp mesh, and the
    loss trajectory matches a plain single-device dygraph run of the same
    stages (same seed/params)."""
    import jax

    from paddle_tpu.distributed.fleet.pipeline import (
        LayerDesc,
        PipelineLayer,
    )
    from paddle_tpu.models.gpt import (
        GPTDecoderLayer,
        GPTEmbeddings,
        gpt_tiny,
    )

    cfg = gpt_tiny(hidden_size=16, num_layers=3, num_heads=2, vocab_size=32,
                   max_position_embeddings=16)

    class Head(nn.Layer):
        def __init__(self, cfg):
            super().__init__()
            self.ln = nn.LayerNorm(cfg.hidden_size)
            self.proj = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                  bias_attr=False)

        def forward(self, h):
            return self.proj(self.ln(h))

    class CE(nn.Layer):
        def forward(self, logits, labels):
            import paddle_tpu.nn.functional as F
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1])).mean()

    def build():
        paddle.framework.random.seed(21)
        descs = ([LayerDesc(GPTEmbeddings, cfg)]
                 + [LayerDesc(GPTDecoderLayer, cfg)
                    for _ in range(cfg.num_layers)]
                 + [LayerDesc(Head, cfg)])
        return PipelineLayer(descs, num_stages=2, loss_fn=CE())

    rng = np.random.default_rng(5)
    B, T = 8, 8
    ids = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)

    mesh = dist.ProcessMesh(shape=[2, 2], dim_names=["pp", "dp"])
    pl = build()
    o1 = opt.SGD(learning_rate=0.05, parameters=pl.parameters())
    eng = dist.Engine(pl, optimizer=o1, mesh=mesh, pp_axis="pp",
                      num_microbatches=4)

    def loader():
        class L:
            def __iter__(self):
                yield [paddle.to_tensor(ids), paddle.to_tensor(labels)]

        return L()

    hist = eng.fit(loader(), epochs=2)
    assert len(hist) == 2

    # reference: eager run of the SAME stage partition, same microbatch
    # loss averaging, single device
    ref = build()
    o2 = opt.SGD(learning_rate=0.05, parameters=ref.parameters())
    mb = B // 4
    ce = CE()
    ref_losses = []
    for _ in range(2):
        total = None
        for i in range(4):
            out = ref.forward(paddle.to_tensor(ids[i * mb:(i + 1) * mb]))
            li = ce(out, paddle.to_tensor(labels[i * mb:(i + 1) * mb]))
            total = li if total is None else total + li
        loss = total / 4
        loss.backward()
        o2.step()
        o2.clear_grad()
        ref_losses.append(float(loss.numpy()))

    np.testing.assert_allclose(hist, ref_losses, rtol=2e-4, atol=1e-5)


def test_engine_pipeline_evaluate_and_default_pp_axis():
    """Review r3: Engine.evaluate on a PipelineLayer must not crash, and a
    PipelineLayer DistModel defaults pp_axis to the 'pp' mesh dim."""
    from paddle_tpu.distributed.fleet.pipeline import (
        LayerDesc,
        PipelineLayer,
    )

    D = 8
    paddle.framework.random.seed(3)
    descs = [LayerDesc(nn.Linear, in_features=D, out_features=D)
             for _ in range(2)]
    pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
    o = opt.SGD(learning_rate=0.05, parameters=pl.parameters())
    mesh = dist.ProcessMesh(shape=[2, 2], dim_names=["pp", "dp"])
    eng = dist.Engine(pl, optimizer=o, mesh=mesh, num_microbatches=2)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(4, D)).astype(np.float32)
    Y = rng.normal(size=(4, D)).astype(np.float32)

    def loader():
        class L:
            def __iter__(self):
                yield [paddle.to_tensor(X), paddle.to_tensor(Y)]

        return L()

    hist = eng.fit(loader(), epochs=1)  # pp_axis defaulted to "pp"
    assert np.isfinite(hist[0])
    ev = eng.evaluate(loader())
    assert np.isfinite(ev["loss"])
    preds = eng.predict(loader())
    assert list(preds[0].shape) == [4, D]
