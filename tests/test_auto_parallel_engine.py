"""Auto-parallel static Engine tests (VERDICT #6): dist.to_static + Engine
train a GPT fixture on the 8-device mesh; losses match the dygraph run.
Pattern: test/auto_parallel/ engine tests with the get_gpt_model fixture.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_parallel.static_engine import (
    choose_batch_axis,
    complete_annotations,
    estimate_cost,
)


def _make_data(n=32, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, 1)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    return X, Y


def _loader(X, Y, bsz):
    def gen():
        for i in range(0, len(X), bsz):
            yield [paddle.to_tensor(X[i:i + bsz]),
                   paddle.to_tensor(Y[i:i + bsz])]

    class L:
        def __iter__(self):
            return gen()

    return L()


def test_completion_pass_defaults_to_replicate():
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    paddle.framework.random.seed(0)
    m = nn.Linear(4, 4)
    ann = complete_annotations(m, mesh)
    assert len(ann) == 2
    for pls in ann.values():
        assert len(pls) == 2
        assert all(type(p).__name__ == "Replicate" for p in pls)


def test_cost_model_prefers_bigger_dp():
    mesh = dist.ProcessMesh(shape=[4, 2], dim_names=["a", "b"])
    paddle.framework.random.seed(0)
    m = nn.Linear(64, 64)
    c4 = estimate_cost(m, mesh, "a", batch_size=32)
    c2 = estimate_cost(m, mesh, "b", batch_size=32)
    # compute dominates at this size: dp=4 is cheaper per device
    assert c4.flops_per_dev < c2.flops_per_dev
    assert choose_batch_axis(m, mesh, 32) in ("a", "b")


def test_dist_model_trains_and_matches_dygraph():
    X, Y = _make_data()
    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])

    def build():
        paddle.framework.random.seed(42)
        m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
        return m, o

    # static engine run
    m1, o1 = build()
    dm = dist.to_static(m1, _loader(X, Y, 16), nn.MSELoss(), o1, mesh=mesh)
    dm.train()
    static_losses = []
    for xb, yb in _loader(X, Y, 16):
        static_losses.append(float(dm(xb, yb).numpy()))

    # dygraph run, same seed/data
    m2, o2 = build()
    lossfn = nn.MSELoss()
    dy_losses = []
    for xb, yb in _loader(X, Y, 16):
        loss = lossfn(m2(xb), yb)
        loss.backward()
        o2.step()
        o2.clear_grad()
        dy_losses.append(float(loss.numpy()))

    np.testing.assert_allclose(static_losses, dy_losses, rtol=2e-4,
                               atol=1e-6)
    # params end identical too
    for p, q in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), rtol=2e-4,
                                   atol=1e-5)


def test_engine_fit_evaluate_gpt_fixture():
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    cfg = gpt_tiny(hidden_size=16, num_layers=2, num_heads=2, vocab_size=32,
                   max_position_embeddings=16)

    class CE(nn.Layer):
        def forward(self, logits, labels):
            import paddle_tpu.nn.functional as F
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1])).mean()

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (16, 8)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (16, 8)).astype(np.int32)

    def loader():
        class L:
            def __iter__(self):
                for i in range(0, 16, 8):
                    yield [paddle.to_tensor(ids[i:i + 8]),
                           paddle.to_tensor(labels[i:i + 8])]

        return L()

    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
    paddle.framework.random.seed(7)
    model = GPTForCausalLM(cfg)
    optimizer = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    eng = dist.Engine(model, CE(), optimizer, mesh=mesh)
    history = eng.fit(loader(), epochs=3)
    assert len(history) == 6
    assert all(np.isfinite(history))
    assert history[-1] < history[0]  # training moves
    ev = eng.evaluate(loader())
    assert np.isfinite(ev["loss"])
