"""Fused AdamW Pallas kernel tests (VERDICT #8): numerics vs the formula and
vs the stock AdamW optimizer; runs through the Pallas interpreter on CPU.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.optimizer import FusedAdamW
from paddle_tpu.ops.pallas.fused_adamw import fused_adamw_flat, pad_flat


def _np_adamw(p, g, m, v, lr, b1p, b2p, beta1, beta2, eps, wd):
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    mh = m2 / (1 - b1p)
    vh = v2 / (1 - b2p)
    p2 = p * (1 - lr * wd)
    return p2 - lr * mh / (np.sqrt(vh) + eps), m2, v2


def test_kernel_matches_formula():
    rng = np.random.default_rng(0)
    n = 8 * 128 * 3
    p = rng.normal(size=n).astype(np.float32)
    g = rng.normal(size=n).astype(np.float32)
    m = rng.normal(size=n).astype(np.float32) * 0.1
    v = np.abs(rng.normal(size=n)).astype(np.float32) * 0.01
    wd = np.where(rng.random(n) > 0.5, 0.01, 0.0).astype(np.float32)

    out_p, out_m, out_v, out_b1, out_b2 = fused_adamw_flat(
        p, g, m, v, wd, 1e-3, 0.9, 0.999, interpret=True)
    ref_p, ref_m, ref_v = _np_adamw(p, g, m, v, 1e-3, 0.9, 0.999,
                                    0.9, 0.999, 1e-8, wd)
    np.testing.assert_allclose(np.asarray(out_p), ref_p, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(out_m), ref_m, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(np.asarray(out_v), ref_v, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(np.asarray(out_b1), 0.9 * 0.9, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out_b2), 0.999 * 0.999, rtol=1e-6)


def test_kernel_multiblock_grid():
    rng = np.random.default_rng(1)
    n = 8 * 128 * 8
    arrs = [rng.normal(size=n).astype(np.float32) for _ in range(4)]
    p, g, m, v = arrs
    v = np.abs(v) * 0.01
    wd = np.zeros(n, np.float32)
    small = fused_adamw_flat(p, g, m, v, wd, 1e-3, 0.9, 0.999,
                             block_rows=8, interpret=True)
    big = fused_adamw_flat(p, g, m, v, wd, 1e-3, 0.9, 0.999,
                           block_rows=64, interpret=True)
    np.testing.assert_allclose(np.asarray(small[0]), np.asarray(big[0]),
                               rtol=1e-6)


def test_fused_optimizer_matches_stock_adamw():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = rng.normal(size=(16, 1)).astype(np.float32)

    def build(fused):
        paddle.framework.random.seed(5)
        m = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 1))
        cls = FusedAdamW if fused else opt.AdamW
        o = cls(learning_rate=1e-2, parameters=m.parameters(),
                weight_decay=0.01)
        return m, o

    m1, o1 = build(True)
    m2, o2 = build(False)
    lossfn = nn.MSELoss()
    for _ in range(4):
        for m, o in ((m1, o1), (m2, o2)):
            loss = lossfn(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            o.step()
            o.clear_grad()
    for p, q in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), rtol=2e-4,
                                   atol=2e-6)


def test_state_dict_roundtrip():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(8, 4)).astype(np.float32)
    Y = rng.normal(size=(8, 1)).astype(np.float32)

    def build():
        paddle.framework.random.seed(9)
        m = nn.Linear(4, 1)
        o = FusedAdamW(learning_rate=1e-2, parameters=m.parameters())
        return m, o

    m1, o1 = build()
    lossfn = nn.MSELoss()
    for _ in range(3):
        loss = lossfn(m1(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        o1.step()
        o1.clear_grad()
    sd = o1.state_dict()

    m2, o2 = build()
    o2.set_state_dict(sd)
    # continue training both; trajectories must stay identical
    for m, o in ((m1, o1), (m2, o2)):
        loss = lossfn(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        o.step()
        o.clear_grad()
    for p, q in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), rtol=1e-6)


def test_param_set_change_preserves_moments():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(8, 4)).astype(np.float32)
    Y = rng.normal(size=(8, 1)).astype(np.float32)
    paddle.framework.random.seed(10)
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    o = FusedAdamW(learning_rate=1e-2, parameters=m.parameters())
    lossfn = nn.MSELoss()
    for _ in range(3):
        loss = lossfn(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        o.step()
        o.clear_grad()
    import jax.numpy as jnp
    m_before = np.asarray(o._flat["m"])
    b1p_before = float(np.asarray(o._flat["b1pow"]).min())
    assert np.abs(m_before).max() > 0
    # freeze the first layer: grad-bearing set shrinks
    for p in m[0].parameters():
        p.stop_gradient = True
        p.trainable = False
    loss = lossfn(m(paddle.to_tensor(X)), paddle.to_tensor(Y))
    loss.backward()
    o.step()
    # surviving params kept their (nonzero) moments and the pow chain
    # surviving elements advanced their pow chain (not reset to beta)
    assert float(np.asarray(o._flat["b1pow"]).min()) < b1p_before
    assert np.abs(np.asarray(o._flat["m"])).max() > 0


def test_pad_flat_roundtrip():
    import jax.numpy as jnp
    a = np.arange(10, dtype=np.float32)
    b = np.arange(6, dtype=np.float32).reshape(2, 3)
    flat, sizes, padded = pad_flat([jnp.asarray(a), jnp.asarray(b)])
    assert padded % (8 * 128) == 0
    assert sizes == [10, 6]
    np.testing.assert_allclose(np.asarray(flat[:10]), a)
    np.testing.assert_allclose(np.asarray(flat[10:16]).reshape(2, 3), b)


def test_trainstep_fused_mode_matches_stock(monkeypatch):
    """TrainStep(FusedAdamW) must produce the same loss trajectory as
    TrainStep(AdamW) — both through the default per-param path AND through
    the opt-in flat mode (PADDLE_TPU_FUSED_FLAT=1). Context (VERDICT r2
    weak #5 / r3 #6): the flat-master in-graph formulation measured 0.645x
    on-chip (AD slice-transpose cost), so the DEFAULT inside TrainStep is
    the per-param path where XLA's own fusion applies; the flat mode stays
    available and must stay numerically exact."""
    import numpy as np

    monkeypatch.setenv("PADDLE_TPU_FUSED_FLAT", "1")

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.optimizer import FusedAdamW
    from paddle_tpu.jit.api import TrainStep

    rng = np.random.default_rng(7)
    X = rng.normal(size=(16, 8)).astype(np.float32)
    Y = rng.normal(size=(16, 4)).astype(np.float32)

    def build():
        paddle.framework.random.seed(99)
        return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))

    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        return mse(m(x), y)

    def run(optimizer_cls):
        model = build()
        o = optimizer_cls(learning_rate=0.01, parameters=model.parameters(),
                          weight_decay=0.01)
        step = TrainStep(model, loss_fn, o)
        xs, ys = paddle.to_tensor(X), paddle.to_tensor(Y)
        return [float(step(xs, ys).numpy()) for _ in range(4)], model

    stock_losses, _ = run(opt.AdamW)
    fused_losses, fmodel = run(FusedAdamW)
    np.testing.assert_allclose(fused_losses, stock_losses, rtol=2e-5,
                               atol=1e-6)
    # the fused step wrote updated params back into the live tensors
    assert not np.allclose(fmodel.state_dict()["0.weight"].numpy(),
                           build().state_dict()["0.weight"].numpy())


def test_trainstep_fused_mode_engaged(monkeypatch):
    import numpy as np

    monkeypatch.setenv("PADDLE_TPU_FUSED_FLAT", "1")

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.optimizer import FusedAdamW
    from paddle_tpu.jit.api import TrainStep

    model = nn.Linear(4, 4)
    o = FusedAdamW(learning_rate=0.01, parameters=model.parameters())
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, x, y: mse(m(x), y), o)
    assert step._fused_mode
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = step(x, x)
    assert np.isfinite(float(loss.numpy()))
    assert step._fused_jitted is not None  # flat path actually compiled


def test_trainstep_fused_default_uses_per_param_path():
    """Default (no env flag): FusedAdamW rides the stock per-param update
    inside TrainStep — same speed as AdamW by construction — and its
    checkpoint surface stays populated."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.optimizer import FusedAdamW
    from paddle_tpu.jit.api import TrainStep

    model = nn.Linear(4, 4)
    o = FusedAdamW(learning_rate=0.01, parameters=model.parameters())
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, x, y: mse(m(x), y), o)
    assert not step._fused_mode
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(2):
        loss = step(x, x)
    assert np.isfinite(float(loss.numpy()))
    sd = o.state_dict()
    assert sd.get("states"), "per-param checkpoint surface must be populated"
    # flat build after per-param stepping seeds moments (no silent zeroing)
    o._build_flat([(p, None) for p in o._parameter_list if p.trainable])
    assert float(abs(np.asarray(o._flat["m"])).sum()) > 0


def test_fused_linear_cross_entropy_matches_naive():
    """Chunked lm-head CE == naive logits CE, values AND grads (h, w)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.incubate.nn.functional.fused_linear_ce import (
        fused_linear_cross_entropy,
    )

    rng = np.random.default_rng(0)
    T, D, V = 24, 16, 32
    h = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(V, D)).astype(np.float32) * 0.2)
    labels = jnp.asarray(rng.integers(0, V, (T,)).astype(np.int32))
    labels = labels.at[3].set(-100)  # ignore_index entry

    def naive(h_, w_):
        logits = (h_ @ w_.T).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, jnp.clip(labels, 0, V - 1)[:, None], axis=1)[:, 0]
        valid = labels != -100
        return jnp.sum(jnp.where(valid, lse - picked, 0.0)) / jnp.sum(valid)

    def fused(h_, w_):
        return fused_linear_cross_entropy(h_, w_, labels, 4)

    l_ref, (gh_ref, gw_ref) = jax.value_and_grad(naive, argnums=(0, 1))(h, w)
    l_got, (gh, gw) = jax.value_and_grad(fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(float(l_got), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                               rtol=1e-4, atol=1e-6)


def test_fused_linear_cross_entropy_under_jit_bf16():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.incubate.nn.functional.fused_linear_ce import (
        fused_linear_cross_entropy,
    )

    rng = np.random.default_rng(1)
    T, D, V = 16, 8, 16
    h = jnp.asarray(rng.normal(size=(T, D)).astype(np.float32),
                    dtype=jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(V, D)).astype(np.float32) * 0.2,
                    dtype=jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, V, (T,)).astype(np.int32))
    loss = jax.jit(lambda a, b: fused_linear_cross_entropy(a, b, labels, 2))(
        h, w)
    assert np.isfinite(float(loss))
