"""DistributedFusedLamb: flat-buffer fused update vs the per-tensor Lamb
oracle (reference semantics: distributed_fused_lamb.py — same math, fused)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.optimizer import DistributedFusedLamb


def _build(seed):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 4))


def _run(model, optimizer, steps=4):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 6)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    mse = nn.MSELoss()
    losses = []
    for _ in range(steps):
        loss = mse(model(x), y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


def test_fused_matches_per_tensor_lamb():
    m1 = _build(0)
    o1 = opt.Lamb(learning_rate=1e-2, lamb_weight_decay=0.01,
                  parameters=m1.parameters())
    ref = _run(m1, o1)

    m2 = _build(0)
    o2 = DistributedFusedLamb(learning_rate=1e-2, lamb_weight_decay=0.01,
                              parameters=m2.parameters())
    fused = _run(m2, o2)

    np.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-6)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-5,
                                   atol=1e-6)


def test_fused_lamb_exclude_weight_decay():
    m = _build(1)
    biases = {id(p) for p in m.parameters() if len(p.shape) == 1}
    o = DistributedFusedLamb(
        learning_rate=1e-2, lamb_weight_decay=0.5,
        parameters=m.parameters(),
        exclude_from_weight_decay_fn=lambda p: id(p) in biases)
    # oracle: per-tensor Lamb with the same exclusion
    m2 = _build(1)
    o2 = opt.Lamb(learning_rate=1e-2, lamb_weight_decay=0.5,
                  parameters=m2.parameters(),
                  exclude_from_weight_decay_fn=lambda p: len(p.shape) == 1)
    f = _run(m, o)
    r = _run(m2, o2)
    np.testing.assert_allclose(f, r, rtol=1e-5, atol=1e-6)


def test_fused_lamb_state_roundtrip():
    m = _build(2)
    o = DistributedFusedLamb(learning_rate=1e-2, parameters=m.parameters())
    _run(m, o, steps=2)
    sd = o.state_dict()

    m2 = _build(2)
    o2 = DistributedFusedLamb(learning_rate=1e-2, parameters=m2.parameters())
    _run(m2, o2, steps=2)  # advance then overwrite
    o2.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(o2._m1), np.asarray(o._m1),
                               rtol=1e-6)
    assert float(o2._flat_step) == float(o._flat_step)


def test_no_grad_param_is_frozen():
    """A trainable param with no gradient must not decay (reference skips
    gradless params entirely)."""
    m = _build(4)
    o = DistributedFusedLamb(learning_rate=1e-2, lamb_weight_decay=0.5,
                             parameters=m.parameters())
    frozen = m[2]  # last Linear never used in forward below
    before = {id(p): p.numpy().copy() for p in frozen.parameters()}
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 6)).astype(np.float32))
    mse = nn.MSELoss()
    for _ in range(3):
        h = m[1](m[0](x))  # only first two layers
        loss = mse(h, paddle.to_tensor(np.zeros((8, 8), np.float32)))
        loss.backward()
        o.step()
        o.clear_grad()
    for p in frozen.parameters():
        np.testing.assert_array_equal(p.numpy(), before[id(p)])
