"""Hand-written Pallas fused RMSNorm: interpret-mode equality (fwd + bwd)
vs the XLA composition, padding path, and the incubate routing gate."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_rms_norm import rms_norm_pallas, rms_ref

EPS = 1e-6


def _ref(x, w):
    return rms_ref(x, w, EPS)


@pytest.mark.parametrize("n,d", [(256, 256), (100, 512), (7, 128)])
def test_forward_equality(n, d):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    out = rms_norm_pallas(x, w, EPS, 128, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, w)),
                               rtol=1e-5, atol=1e-5)


def test_grad_equality():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(96, 256)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(256,)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(96, 256)).astype(np.float32))

    def loss_k(x_, w_):
        return jnp.sum(rms_norm_pallas(x_, w_, EPS, 128, True) * g)

    def loss_r(x_, w_):
        return jnp.sum(_ref(x_, w_) * g)

    dxk, dwk = jax.grad(loss_k, argnums=(0, 1))(x, w)
    dxr, dwr = jax.grad(loss_r, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dxk), np.asarray(dxr),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(dwk), np.asarray(dwr),
                               rtol=2e-4, atol=2e-5)


def test_bf16_forward():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(64, 128))).astype(jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(128,))).astype(jnp.bfloat16)
    out = rms_norm_pallas(x, w, EPS, 64, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(_ref(x, w), np.float32),
        rtol=2e-2, atol=2e-2)


def test_incubate_routing_gate():
    """On CPU the gate stays off (XLA composition, _last_path="xla"); the
    general (residual) path never touches the kernel."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import functional as IF
    from paddle_tpu.ops.pallas import fused_rms_norm as frn

    x = paddle.to_tensor(np.random.default_rng(3).normal(
        size=(4, 8, 256)).astype(np.float32))
    w = paddle.to_tensor(np.ones((256,), np.float32))
    assert not frn.use_fused_rms_norm(256)  # CPU platform
    out = IF.fused_rms_norm(x, norm_weight=w, epsilon=EPS)
    assert frn._last_path == "xla"
    np.testing.assert_allclose(
        np.asarray(out.numpy()),
        np.asarray(_ref(jnp.asarray(x.numpy()), jnp.asarray(w.numpy()))),
        rtol=1e-5, atol=1e-5)


def test_router_kernel_path_end_to_end(monkeypatch):
    """Force the gate ON (interpret mode) and drive the PRODUCTION call
    shape through nn.functional.rms_norm — the path nn.RMSNorm / LLaMA
    use — asserting the kernel actually ran (_last_path) with correct
    values AND grads through the tape."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.ops.pallas import fused_rms_norm as frn

    monkeypatch.setattr(frn, "use_fused_rms_norm", lambda d: True)
    monkeypatch.setattr(frn, "_interpret", True)

    rng = np.random.default_rng(4)
    x_np = rng.normal(size=(4, 8, 256)).astype(np.float32)
    w_np = rng.normal(size=(256,)).astype(np.float32)

    layer = nn.RMSNorm(256, epsilon=EPS)
    layer.weight.set_value(paddle.to_tensor(w_np))
    xt = paddle.to_tensor(x_np)
    xt.stop_gradient = False
    out = layer(xt)
    assert frn._last_path == "pallas"
    np.testing.assert_allclose(
        np.asarray(out.numpy()),
        np.asarray(_ref(jnp.asarray(x_np), jnp.asarray(w_np))),
        rtol=1e-5, atol=1e-5)

    out.sum().backward()
    gk = np.asarray(xt.grad.numpy())
    gw = np.asarray(layer.weight.grad.numpy())

    def ref_loss(xv, wv):
        return jnp.sum(rms_ref(xv, wv, EPS))

    gr, gwr = jax.grad(ref_loss, argnums=(0, 1))(
        jnp.asarray(x_np), jnp.asarray(w_np))
    np.testing.assert_allclose(gk, np.asarray(gr), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(gw, np.asarray(gwr), rtol=2e-4, atol=2e-5)
