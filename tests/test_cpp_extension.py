"""Custom C++ op extension (parity: python/paddle/utils/cpp_extension +
test/custom_op/ custom_relu pattern): JIT-compile, run, differentiate."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

SRC = r"""
#include <cstdint>
#include <cmath>

extern "C" void custom_relu_fwd(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0.f ? x[i] : 0.f;
}

extern "C" void custom_relu_bwd(const float* x, const float* dy, float* dx,
                                int64_t n) {
  for (int64_t i = 0; i < n; ++i) dx[i] = x[i] > 0.f ? dy[i] : 0.f;
}

extern "C" void custom_sqr_fwd(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i];
}
"""


@pytest.fixture(scope="module")
def ops(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "custom_ops.cc"
    src.write_text(SRC)
    return cpp_extension.load(name="custom_jit_ops", sources=[str(src)])


def test_custom_op_forward(ops):
    x = np.array([-1.0, 0.5, 2.0, -3.0], np.float32)
    out = ops.custom_relu(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), np.maximum(x, 0))


def test_custom_op_backward(ops):
    x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32),
                         stop_gradient=False)
    y = ops.custom_relu(x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])


def test_custom_op_inside_jit(ops):
    def f(x):
        return ops.custom_relu(x) * 2

    sf = paddle.jit.to_static(f)
    x = paddle.to_tensor(np.array([-2.0, 3.0], np.float32))
    np.testing.assert_allclose(np.asarray(sf(x).numpy()), [0.0, 6.0])


def test_custom_op_without_bwd_not_differentiable(ops):
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    out = ops.custom_sqr(x)
    np.testing.assert_allclose(out.numpy(), [4.0])
    assert out.stop_gradient  # recorded as non-differentiable


def test_custom_op_in_layer_training(ops):
    import paddle_tpu.nn as nn

    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return ops.custom_relu(self.fc(x))

    net = Net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    mse = nn.MSELoss()
    l0 = None
    for _ in range(5):
        loss = mse(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        l0 = l0 or float(loss.numpy())
    assert float(loss.numpy()) < l0


def test_ffi_device_path_engaged(ops):
    """r3 (VERDICT r2 missing #6): on the CPU backend the op must run as a
    real XLA FFI custom call (inside the program, no python callback), not
    through pure_callback."""
    import jax

    assert jax.default_backend() == "cpu"
    assert ops._ffi_name is not None, \
        "FFI wrapper build/registration failed — device path not engaged"
    # the custom call appears in the lowered HLO (pure_callback would show
    # as 'callback' / py_callback custom-call instead)
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))

    def f(xv):
        return ops.custom_relu(paddle.Tensor._from_value(xv))._value

    hlo = jax.jit(f).lower(x._value).as_text()
    assert "paddle_tpu_custom_jit_ops_custom_relu_fwd" in hlo
    assert "py_callback" not in hlo.lower()


def test_ffi_backward_matches_reference(ops):
    import jax

    if ops._ffi_name is None:
        pytest.skip("ffi unavailable")
    x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32))
    x.stop_gradient = False
    y = ops.custom_relu(x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])
