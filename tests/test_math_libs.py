"""fft / distribution / sparse / quantization / static (reference patterns:
test/legacy_test/test_fft.py, test/distribution/, test_sparse_*.py,
test/quantization/)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_fft_roundtrip(rng):
    x = rng.standard_normal(16).astype(np.float32)
    back = paddle.fft.ifft(paddle.fft.fft(paddle.to_tensor(x)))
    np.testing.assert_allclose(back.numpy().real, x, atol=1e-5)


def test_rfft_matches_numpy(rng):
    x = rng.standard_normal((4, 16)).astype(np.float32)
    out = paddle.fft.rfft(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), np.fft.rfft(x), rtol=1e-4,
                               atol=1e-4)


def test_fft2_and_shift(rng):
    x = rng.standard_normal((8, 8)).astype(np.float32)
    out = paddle.fft.fftshift(paddle.fft.fft2(paddle.to_tensor(x)))
    ref = np.fft.fftshift(np.fft.fft2(x))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_normal_distribution_moments():
    paddle.seed(0)
    d = paddle.distribution.Normal(2.0, 3.0)
    s = d.sample((20000,)).numpy()
    assert abs(s.mean() - 2.0) < 0.1
    assert abs(s.std() - 3.0) < 0.1
    # analytic entropy
    ent = float(d.entropy().numpy())
    assert abs(ent - (0.5 + 0.5 * np.log(2 * np.pi) + np.log(3.0))) < 1e-5


def test_normal_kl_closed_form():
    p = paddle.distribution.Normal(0.0, 1.0)
    q = paddle.distribution.Normal(1.0, 2.0)
    kl = float(paddle.distribution.kl_divergence(p, q).numpy())
    expected = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    assert abs(kl - expected) < 1e-5


def test_categorical_log_prob():
    d = paddle.distribution.Categorical(
        logits=paddle.to_tensor(np.log(np.array([0.2, 0.3, 0.5], np.float32))))
    lp = d.log_prob(paddle.to_tensor(np.array([2], np.int64)))
    np.testing.assert_allclose(lp.numpy(), [np.log(0.5)], rtol=1e-5)


def test_beta_kl_vs_sampling():
    p = paddle.distribution.Beta(2.0, 3.0)
    q = paddle.distribution.Beta(3.0, 2.0)
    kl = float(paddle.distribution.kl_divergence(p, q).numpy())
    assert kl > 0
    kl_self = float(paddle.distribution.kl_divergence(p, p).numpy())
    assert abs(kl_self) < 1e-6


def test_distribution_log_prob_grad():
    mu = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
    d = paddle.distribution.Normal(mu, 1.0)
    lp = d.log_prob(paddle.to_tensor(np.float32(2.0)))
    lp.backward()
    # d/dmu log N(2; mu, 1) = (2 - mu) = 1.5
    np.testing.assert_allclose(mu.grad.numpy(), 1.5, rtol=1e-5)


def test_sparse_coo_roundtrip():
    st = paddle.sparse.sparse_coo_tensor(
        [[0, 0, 2], [0, 3, 1]], [1.0, 2.0, 3.0], shape=[3, 4])
    assert st.nnz() == 3
    dense = st.to_dense().numpy()
    assert dense[0, 0] == 1.0 and dense[0, 3] == 2.0 and dense[2, 1] == 3.0
    back = paddle.sparse.to_sparse_coo(paddle.to_tensor(dense))
    np.testing.assert_allclose(back.to_dense().numpy(), dense)


def test_sparse_csr_and_matmul(rng):
    dense = np.array([[1, 0, 2], [0, 0, 3]], np.float32)
    st = paddle.sparse.sparse_csr_tensor(
        [0, 2, 3], [0, 2, 2], [1.0, 2.0, 3.0], shape=[2, 3])
    np.testing.assert_allclose(st.to_dense().numpy(), dense)
    y = rng.standard_normal((3, 5)).astype(np.float32)
    out = paddle.sparse.matmul(st, paddle.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), dense @ y, rtol=1e-5, atol=1e-5)


def test_sparse_unary_keeps_structure():
    st = paddle.sparse.sparse_coo_tensor([[0], [1]], [-2.0], shape=[2, 2])
    r = paddle.sparse.relu(st)
    assert r.nnz() == 1
    assert r.to_dense().numpy()[0, 1] == 0.0


def test_qat_fake_quant_trains():
    from paddle_tpu.quantization import (
        FakeQuanterWithAbsMaxObserver,
        QAT,
        QuantConfig,
    )

    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                      weight=FakeQuanterWithAbsMaxObserver)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    qmodel = QAT(cfg).quantize(model)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=qmodel.parameters())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 2, (16,)).astype(np.int64))
    ce = nn.CrossEntropyLoss()
    first = None
    for _ in range(30):
        loss = ce(qmodel(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    assert float(loss.numpy()) < first


def test_quantize_dequantize_roundtrip(rng):
    from paddle_tpu.quantization import dequantize_linear, quantize_linear

    x = rng.standard_normal(100).astype(np.float32)
    scale = paddle.to_tensor(np.float32(np.abs(x).max() / 127))
    q = quantize_linear(paddle.to_tensor(x), scale)
    deq = dequantize_linear(q, scale)
    assert np.abs(deq.numpy() - x).max() < float(scale.numpy())


def test_static_executor():
    from paddle_tpu import static

    spec = static.InputSpec([None, 4], "float32", name="x")
    assert spec.shape == (None, 4)
    prog = static.Program.from_callable(lambda x: x * 2 + 1)
    exe = static.Executor()
    out = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)})
    np.testing.assert_allclose(out[0], 3.0)
