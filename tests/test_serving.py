"""Serving path: static/paged KV caches, jitted DecodeEngine, fused serving
attention ops. Oracles: the eager concat-cache generate() path (itself
verified cached==full-context) and naive numpy attention."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (
    DecodeEngine,
    GPTForCausalLM,
    LlamaForCausalLM,
    gpt_tiny,
    llama_tiny,
)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts decode-program numerics even with a
    same-build cache (see test_serving_sched.py); serving tests compile
    fresh instead of replaying from the persistent cache."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _gpt():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _llama():
    paddle.seed(11)
    return LlamaForCausalLM(llama_tiny())


def test_engine_matches_eager_greedy_gpt():
    model = _gpt()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1000, (2, 12))
    eager = model.generate(paddle.to_tensor(ids.astype(np.int64)),
                           max_new_tokens=8, temperature=0.0)
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.0)
    out = engine.generate(ids, max_new_tokens=8)
    eager_np = np.asarray(eager.numpy())
    for i in range(2):
        np.testing.assert_array_equal(out[i], eager_np[i])


def test_engine_matches_eager_greedy_llama():
    model = _llama()
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1024, (2, 10))
    eager = model.generate(paddle.to_tensor(ids.astype(np.int64)),
                           max_new_tokens=6, temperature=0.0)
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.0)
    out = engine.generate(ids, max_new_tokens=6)
    eager_np = np.asarray(eager.numpy())
    for i in range(2):
        np.testing.assert_array_equal(out[i], eager_np[i])


def test_engine_ragged_batch_matches_individual():
    """Two prompts of different lengths in one padded batch must decode the
    same tokens as each prompt alone."""
    model = _gpt()
    rng = np.random.default_rng(2)
    a = rng.integers(0, 1000, 11)
    b = rng.integers(0, 1000, 5)
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.0)

    batch = np.zeros((2, 11), np.int64)
    batch[0] = a
    batch[1, :5] = b
    out = engine.generate(batch, seq_lens=[11, 5], max_new_tokens=6)

    solo_a = engine.generate(a[None, :], max_new_tokens=6)[0]
    solo_b = engine.generate(b[None, :], max_new_tokens=6)[0]
    np.testing.assert_array_equal(out[0], solo_a)
    np.testing.assert_array_equal(out[1], solo_b)


def test_paged_engine_matches_dense():
    model = _gpt()
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1000, (2, 9))
    dense = DecodeEngine(model, max_seq_len=64, temperature=0.0)
    paged = DecodeEngine(model, max_seq_len=64, temperature=0.0,
                         use_paged=True, block_size=8)
    out_d = dense.generate(ids, max_new_tokens=7)
    out_p = paged.generate(ids, max_new_tokens=7)
    for d, p in zip(out_d, out_p):
        np.testing.assert_array_equal(d, p)


def test_engine_eos_trims():
    model = _gpt()
    rng = np.random.default_rng(4)
    ids = rng.integers(0, 1000, (1, 8))
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.0)
    base = engine.generate(ids, max_new_tokens=6)[0]
    eos = int(base[9])  # second generated token becomes "eos"
    out = engine.generate(ids, max_new_tokens=6, eos_token_id=eos)[0]
    assert out[-1] == eos
    assert len(out) == 10
    np.testing.assert_array_equal(out, base[:10])


def test_engine_sampled_decoding_runs():
    model = _gpt()
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 1000, (2, 8))
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.8, top_k=5)
    out = engine.generate(ids, max_new_tokens=5)
    assert all(len(o) == 13 for o in out)
    assert all(o.min() >= 0 and o.max() < 1024 for o in out)


def test_decode_step_no_recompile():
    """Every decode step after the first must hit the jit program cache."""
    model = _gpt()
    rng = np.random.default_rng(6)
    ids = rng.integers(0, 1000, (1, 8))
    engine = DecodeEngine(model, max_seq_len=64, temperature=0.0)
    engine.generate(ids, max_new_tokens=4)
    sizes = engine._sf._jitted._cache_size()
    engine.generate(ids, max_new_tokens=12)
    assert engine._sf._jitted._cache_size() == sizes  # prefill+decode reused


def _naive_decode_attention(q, ck, cv, lens):
    """numpy oracle: one query vs cached prefix (incl. the new token)."""
    B, _, H, D = q.shape
    out = np.zeros((B, H, D), np.float32)
    for b in range(B):
        L = lens[b] + 1
        for h in range(H):
            s = (ck[b, :L, h] @ q[b, 0, h]) / np.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ cv[b, :L, h]
    return out


def test_masked_multihead_attention_op():
    from paddle_tpu.incubate.nn import functional as IF

    rng = np.random.default_rng(7)
    B, H, D, ML = 2, 3, 8, 16
    lens = np.array([5, 9], np.int32)
    cache = np.zeros((2, B, ML, H, D), np.float32)
    for b in range(B):
        cache[:, b, :lens[b]] = rng.standard_normal((2, lens[b], H, D))
    x = rng.standard_normal((B, 3, H, D)).astype(np.float32)

    out, new_cache = IF.masked_multihead_attention(
        paddle.to_tensor(x), paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(lens))

    nc = np.asarray(new_cache.numpy())
    # new token K/V written at position lens[b]
    for b in range(B):
        np.testing.assert_allclose(nc[0, b, lens[b]], x[b, 1], rtol=1e-6)
        np.testing.assert_allclose(nc[1, b, lens[b]], x[b, 2], rtol=1e-6)
    oracle = _naive_decode_attention(
        x[:, 0:1], nc[0], nc[1], lens).reshape(B, H * D)
    np.testing.assert_allclose(np.asarray(out.numpy()), oracle,
                               rtol=1e-4, atol=1e-5)


def test_block_multihead_attention_op_matches_dense():
    from paddle_tpu.incubate.nn import functional as IF
    from paddle_tpu.models.kv_cache import BlockAllocator

    rng = np.random.default_rng(8)
    B, H, D, bs = 2, 2, 4, 4
    lens = np.array([6, 3], np.int32)
    alloc = BlockAllocator(num_blocks=8, block_size=bs)
    tables = np.full((B, 3), -1, np.int32)
    for b in range(B):
        blks = alloc.allocate(lens[b] + 1)
        tables[b, :len(blks)] = blks

    kp = np.zeros((8, bs, H, D), np.float32)
    vp = np.zeros((8, bs, H, D), np.float32)
    dense_k = np.zeros((B, 12, H, D), np.float32)
    dense_v = np.zeros((B, 12, H, D), np.float32)
    for b in range(B):
        for t in range(lens[b]):
            kv = rng.standard_normal((2, H, D)).astype(np.float32)
            blk, off = tables[b, t // bs], t % bs
            kp[blk, off], vp[blk, off] = kv[0], kv[1]
            dense_k[b, t], dense_v[b, t] = kv[0], kv[1]

    qkv = rng.standard_normal((B, 1, 3, H, D)).astype(np.float32)
    out, kp2, vp2 = IF.block_multihead_attention(
        paddle.to_tensor(qkv), paddle.to_tensor(kp), paddle.to_tensor(vp),
        paddle.to_tensor(lens), paddle.to_tensor(tables))

    for b in range(B):
        dense_k[b, lens[b]] = qkv[b, 0, 1]
        dense_v[b, lens[b]] = qkv[b, 0, 2]
    oracle = _naive_decode_attention(
        qkv[:, :, 0], dense_k, dense_v, lens).reshape(B, 1, H * D)
    np.testing.assert_allclose(np.asarray(out.numpy()), oracle,
                               rtol=1e-4, atol=1e-5)
