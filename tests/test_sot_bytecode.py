"""Bytecode-tier SOT tests (VERDICT r2 missing #1 / next-round #5).

Reference pattern: test/sot/test_01_basic.py — run the same function eager
vs captured, assert equality. The decisive capability beyond round 2's
function-level tier: a frame with `.numpy()` (or tensor-dependent python
branching) in the MIDDLE becomes compiled-region -> eager gap ->
compiled-region instead of permanently falling back to eager.
"""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.jit.sot import sot_stats, symbolic_translate
from paddle_tpu.jit.sot.bytecode import (
    BytecodeUnsupported,
    CapturedFrame,
    RegionTracer,
)


def t(v, dtype=None):
    return paddle.to_tensor(np.asarray(v, dtype=np.float32), dtype=dtype)


def _eager(fn, *args):
    return fn(*args)


# ---------------------------------------------------------------- basics


def test_straightline_tensor_math():
    def fn(x, y):
        a = x + y * 2.0
        b = a - x / 2.0
        return b * b

    w = symbolic_translate(fn)
    x, y = t([1.0, 2.0]), t([3.0, 4.0])
    np.testing.assert_allclose(w(x, y).numpy(), fn(x, y).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"] and not st["fallback"]
    assert st["bytecode_breaks"] == 0


def test_methods_attrs_and_paddle_calls():
    def fn(x):
        h = paddle.matmul(x, x)
        s = h.sum(axis=0)
        return s.reshape([x.shape[0]]) + float(x.ndim)

    w = symbolic_translate(fn)
    x = t(np.arange(9).reshape(3, 3))
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-5)
    assert sot_stats(w)["bytecode"]


def test_python_loop_single_region():
    def fn(x, n):
        s = x
        for i in range(n):
            s = s + float(i)
        return s * 2.0

    w = symbolic_translate(fn)
    x = t([1.0, 2.0])
    np.testing.assert_allclose(w(x, 4).numpy(), fn(x, 4).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"] and st["bytecode_breaks"] == 0


# ------------------------------------------------- sub-function graph breaks


def test_mid_function_numpy_break_keeps_capture():
    """THE round-3 capability: .numpy() mid-frame splits the frame into
    two compiled regions + an eager gap — NOT permanent eager fallback."""

    def fn(x):
        a = x * 2.0 + 1.0          # region 1
        host = float(a.numpy().sum())   # eager gap (graph break)
        b = x - host               # region 2 (seeded by the host value)
        return b * 3.0

    w = symbolic_translate(fn)
    x = t([1.0, 2.0, 3.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"], "frame must stay on the bytecode tier"
    assert not st["fallback"], "must NOT permanently fall back"
    assert st["bytecode_breaks"] >= 1
    assert st["regions_compiled"] >= 1


def test_tensor_branch_is_a_break_not_a_fallback():
    def fn(x):
        a = x * 2.0
        if a.sum() > 0.0:          # tensor-dependent branch -> break
            return a + 10.0
        return a - 10.0

    w = symbolic_translate(fn)
    pos, neg = t([1.0, 2.0]), t([-5.0, -6.0])
    np.testing.assert_allclose(w(pos).numpy(), fn(pos).numpy(), rtol=1e-6)
    np.testing.assert_allclose(w(neg).numpy(), fn(neg).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"] and not st["fallback"]
    assert st["bytecode_breaks"] >= 2  # one per call (both sides exercised)


def test_unknown_callable_is_an_eager_gap():
    def hostside(arr):
        # not a paddle/jax/operator callable: must run as an eager gap
        return float(np.asarray(arr.numpy()).max())

    def fn(x):
        m = hostside(x * 2.0)
        return x + m

    w = symbolic_translate(fn)
    x = t([1.0, 4.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"] and st["bytecode_breaks"] >= 1


# ------------------------------------------------------- guards & caching


def test_breakfree_frame_promotes_to_whole_graph():
    def fn(x):
        return x * 2.0 + 1.0

    w = symbolic_translate(fn)
    x = t([1.0, 2.0])
    w(x)
    assert sot_stats(w)["interpreted_calls"] == 1
    w(x)  # same guards: whole-graph fast path, no re-interpretation
    assert sot_stats(w)["interpreted_calls"] == 1
    w(t([1.0, 2.0, 3.0]))  # new shape: guard miss -> interpret again
    assert sot_stats(w)["interpreted_calls"] == 2


def test_broken_frame_reinterprets_but_reuses_region_cache():
    from paddle_tpu.jit.sot import bytecode as bc

    def fn(x):
        a = x * 2.0
        h = float(a.numpy().sum())
        return a + h

    w = symbolic_translate(fn)
    x = t([1.0, 2.0])
    w(x)
    st1 = sot_stats(w)
    hits_before = bc.region_cache_stats()["hits"]
    w(x)  # re-interprets (python gap may branch) but regions hit the cache
    st2 = sot_stats(w)
    assert st2["interpreted_calls"] == st1["interpreted_calls"] + 1
    assert bc.region_cache_stats()["hits"] > hits_before


def test_value_dependent_gap_result_feeds_next_region():
    """The eager gap's HOST value flows into the next region each call —
    re-interpretation keeps it faithful when inputs change."""

    def fn(x):
        a = x * 2.0
        h = float(a.numpy().sum())
        if h > 10.0:
            return x + 100.0
        return x - 100.0

    w = symbolic_translate(fn)
    np.testing.assert_allclose(w(t([1.0])).numpy(), fn(t([1.0])).numpy())
    np.testing.assert_allclose(w(t([9.0])).numpy(), fn(t([9.0])).numpy())


# ---------------------------------------------------------------- fallback


def test_unsupported_frame_falls_to_function_tier():
    def fn(x):
        # generator expression inside — outside the supported subset
        return sum(v for v in [1, 2, 3]) + x

    w = symbolic_translate(fn)
    x = t([1.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    # function tier (or eager) answered; bytecode declined gracefully
    assert not sot_stats(w)["bytecode"]


def test_executor_declines_generators_directly():
    def gen(x):
        yield x

    tracer = RegionTracer()
    cf = CapturedFrame(gen)
    try:
        cf(("k",), (t([1.0]),), {})
        raised = False
    except BytecodeUnsupported:
        raised = True
    assert raised


def test_unknown_tensor_attr_is_a_break_not_a_decline():
    """Reading a non-metadata tensor attribute mid-frame materializes the
    tensor (graph break) instead of declining the frame — a decline after
    side effects would re-run them through the fallback tier (review r3)."""
    calls = []

    def fn(x):
        calls.append(1)          # python side effect
        y = x + 1.0
        g = y.grad               # unknown attr -> break, NOT decline
        return y * 2.0 if g is None else y

    w = symbolic_translate(fn)
    x = t([1.0, 2.0])
    out = w(x)
    np.testing.assert_allclose(out.numpy(), (np.asarray([1.0, 2.0]) + 1) * 2)
    assert len(calls) == 1, "side effect must run exactly once"
    st = sot_stats(w)
    assert st["bytecode"] and st["bytecode_breaks"] >= 1


def test_user_exception_propagates_once():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("user error")

    def fn(x):
        boom()
        return x

    w = symbolic_translate(fn)
    try:
        w(t([1.0]))
        raised = False
    except ValueError:
        raised = True
    assert raised
    assert len(calls) == 1, "user code must not be re-executed by a fallback"


def test_tensor_setitem_is_a_break():
    def fn(x):
        a = x * 2.0
        a[0] = 7.0          # in-place write -> graph break
        return a + 1.0

    w = symbolic_translate(fn)
    x = t([1.0, 2.0, 3.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"] and st["bytecode_breaks"] >= 1


def test_setitem_after_pending_read_keeps_order():
    """Review r3: the in-place write must flush pending statements first —
    an earlier-recorded read of the same symbol sees the PRE-mutation
    value (eager semantics)."""
    def fn(x):
        a = x * 2.0
        float(a.numpy()[0])     # materialize a
        c = a + 1.0             # pending read of a
        a[0] = 100.0            # in-place write
        return c

    w = symbolic_translate(fn)
    x = t([1.0, 2.0, 3.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    np.testing.assert_allclose(w(t([1.0, 2.0, 3.0])).numpy(), [3.0, 5.0, 7.0])


def test_setitem_into_raw_tensor_target():
    """Storing a deferred value into a tensor created by a pure-python call
    (never symbolized) must break+write, not crash."""
    def fn(x):
        buf = paddle.zeros([3])
        buf[0] = x[0] * 2.0
        return buf + 1.0

    w = symbolic_translate(fn)
    x = t([4.0, 5.0])
    np.testing.assert_allclose(w(x).numpy(), fn(x).numpy(), rtol=1e-6)
    np.testing.assert_allclose(w(t([4.0, 5.0])).numpy(), [9.0, 1.0, 1.0])


def test_list_comprehension_frames_capture():
    """3.12 inlines list comprehensions (PEP 709) — the executor handles
    LOAD_FAST_AND_CLEAR/RERAISE so such frames no longer decline."""
    def fn(x, ns):
        scaled = [x * n for n in ns]
        total = scaled[0]
        for s in scaled[1:]:
            total = total + s
        return total * 0.5

    w = symbolic_translate(fn)
    x = t([1.0, 2.0])
    np.testing.assert_allclose(w(x, [1, 2, 3]).numpy(),
                               fn(x, [1, 2, 3]).numpy(), rtol=1e-6)
    st = sot_stats(w)
    assert st["bytecode"], "comprehension frame must stay on bytecode tier"


def test_comprehension_variable_shadowing_restored():
    def fn(x, n):
        vals = [n * 10 for n in range(3)]      # shadows the parameter n
        return x * n + float(sum(vals))        # n must be restored

    w = symbolic_translate(fn)
    x = t([1.0])
    np.testing.assert_allclose(w(x, 7).numpy(), fn(x, 7).numpy())
    assert sot_stats(w)["bytecode"]


# ------------------------------------------------------- training frames


def test_training_frame_with_break_has_correct_grads():
    """r4 (VERDICT missing #5): a TRAIN-step frame with a mid-frame
    .numpy() graph break runs region-compiled under the live tape and
    produces the same grads as plain eager execution."""
    def train_frame(w, x, y):
        h = paddle.matmul(x, w)
        gate = float(paddle.mean(h).numpy())     # mid-frame break
        scale = 2.0 if gate > -1e9 else 1.0       # python control flow
        out = h * scale + x
        diff = out - y
        return paddle.mean(diff * diff)

    rng = np.random.default_rng(0)
    w_np = rng.standard_normal((4, 4)).astype(np.float32)
    x_np = rng.standard_normal((2, 4)).astype(np.float32)
    y_np = rng.standard_normal((2, 4)).astype(np.float32)

    # eager reference grads
    w_ref = paddle.to_tensor(w_np.copy(), stop_gradient=False)
    loss_ref = train_frame(w_ref, paddle.to_tensor(x_np),
                           paddle.to_tensor(y_np))
    loss_ref.backward()

    wrapped = symbolic_translate(train_frame)
    w_sot = paddle.to_tensor(w_np.copy(), stop_gradient=False)
    loss = wrapped(w_sot, paddle.to_tensor(x_np), paddle.to_tensor(y_np))
    loss.backward()

    np.testing.assert_allclose(float(loss.numpy()), float(loss_ref.numpy()),
                               rtol=1e-6)
    np.testing.assert_allclose(w_sot.grad.numpy(), w_ref.grad.numpy(),
                               rtol=1e-5, atol=1e-6)
    st = sot_stats(wrapped)
    assert st["bytecode"] and not st["fallback"], st
    assert st["bytecode_breaks"] >= 1, st


def test_training_frame_optimizer_loop_learns():
    """Region-compiled training across steps: an SGD loop through the
    bytecode tier (mid-frame break each step) reduces the loss and matches
    the eager trajectory."""
    import paddle_tpu.optimizer as opt

    def step_frame(m_w, m_b, x, y):
        h = paddle.matmul(x, m_w) + m_b
        probe = float(paddle.mean(h).numpy())    # break inside the step
        out = paddle.tanh(h + (0.0 if probe == probe else 1.0))
        d = out - y
        return paddle.mean(d * d)

    rng = np.random.default_rng(1)
    x_np = rng.standard_normal((8, 4)).astype(np.float32)
    y_np = rng.standard_normal((8, 2)).astype(np.float32)

    def run(wrapper):
        paddle.seed(0)
        w = paddle.to_tensor(
            rng2.standard_normal((4, 2)).astype(np.float32) * 0.3,
            stop_gradient=False)
        b = paddle.to_tensor(np.zeros((2,), np.float32),
                             stop_gradient=False)
        optimizer = opt.SGD(learning_rate=0.1, parameters=[w, b])
        fn = wrapper(step_frame) if wrapper else step_frame
        losses = []
        for _ in range(5):
            loss = fn(w, b, paddle.to_tensor(x_np), paddle.to_tensor(y_np))
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        return losses, fn

    rng2 = np.random.default_rng(2)
    eager_losses, _ = run(None)
    rng2 = np.random.default_rng(2)
    sot_losses, fn = run(symbolic_translate)
    np.testing.assert_allclose(sot_losses, eager_losses, rtol=1e-5)
    assert sot_losses[-1] < sot_losses[0]
    st = sot_stats(fn)
    assert st["bytecode"] and not st["fallback"], st
    assert st["bytecode_breaks"] >= 1, st


def test_training_frame_attribute_params_get_grads():
    """Review r4: params reached via ATTRIBUTE access (not frame args)
    must become region inputs — their grads flow and their values are
    never baked into the region cache."""
    import paddle_tpu.nn as nn

    lin = nn.Linear(4, 4)

    def frame(x):
        h = paddle.matmul(x, lin.weight) + lin.bias
        probe = float(paddle.mean(h).numpy())       # mid-frame break
        out = h * (1.0 if probe == probe else 2.0)
        return paddle.mean(out * out)

    rng = np.random.default_rng(3)
    x_np = rng.standard_normal((2, 4)).astype(np.float32)

    loss_ref = frame(paddle.to_tensor(x_np))
    loss_ref.backward()
    ref_wg = lin.weight.grad.numpy().copy()
    ref_bg = lin.bias.grad.numpy().copy()
    lin.weight.clear_grad()
    lin.bias.clear_grad()

    wrapped = symbolic_translate(frame)
    loss = wrapped(paddle.to_tensor(x_np))
    loss.backward()
    assert lin.weight.grad is not None and lin.bias.grad is not None
    np.testing.assert_allclose(lin.weight.grad.numpy(), ref_wg,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(lin.bias.grad.numpy(), ref_bg,
                               rtol=1e-5, atol=1e-6)

    # no stale baking: mutate the weight, re-run, output must change
    v1 = float(wrapped(paddle.to_tensor(x_np)).numpy())
    lin.weight.set_value(paddle.to_tensor(
        np.asarray(lin.weight.numpy()) * 2.0))
    v2 = float(wrapped(paddle.to_tensor(x_np)).numpy())
    assert abs(v1 - v2) > 1e-6, (v1, v2)


def test_sym_stop_gradient_tracks_inputs():
    """Review r4: frames branching on .stop_gradient must see the real
    flag (it was hard-coded True pre-r4, unobservable then because
    training frames never reached the bytecode tier)."""
    def frame(w, x):
        h = paddle.matmul(x, w)
        if not h.stop_gradient:          # python branch on the sym flag
            h = h * 2.0
        return paddle.mean(h)

    rng = np.random.default_rng(5)
    x_np = rng.standard_normal((2, 3)).astype(np.float32)
    w_t = paddle.to_tensor(rng.standard_normal((3, 3)).astype(np.float32),
                           stop_gradient=False)
    ref = float(frame(w_t, paddle.to_tensor(x_np)).numpy())
    wrapped = symbolic_translate(frame)
    got = float(wrapped(w_t, paddle.to_tensor(x_np)).numpy())
    np.testing.assert_allclose(got, ref, rtol=1e-6)

    # frozen weights take the other branch
    w_f = paddle.to_tensor(np.asarray(w_t.numpy()))  # stop_gradient=True
    ref_f = float(frame(w_f, paddle.to_tensor(x_np)).numpy())
    got_f = float(wrapped(w_f, paddle.to_tensor(x_np)).numpy())
    np.testing.assert_allclose(got_f, ref_f, rtol=1e-6)
    assert abs(ref - ref_f * 2.0) < 1e-5  # branches genuinely differ
