"""paddle.{regularizer,signal,batch,reader,callbacks,sysconfig} parity
(r4 namespace sweep — reference: python/paddle/{regularizer,signal,batch,
reader/decorator,callbacks,sysconfig}.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as opt


# --------------------------------------------------------------- regularizer

def test_l2decay_matches_plain_weight_decay():
    # Momentum applies float weight_decay as an L2 grad penalty; L2Decay
    # must produce the identical trajectory
    def train(wd):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=lin.parameters(), weight_decay=wd)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(3):
            loss = lin(x).sum()
            loss.backward()
            o.step()
            o.clear_grad()
        return lin.weight.numpy()

    np.testing.assert_allclose(train(0.1),
                               train(paddle.regularizer.L2Decay(0.1)),
                               rtol=1e-6)


def test_l1decay_sign_penalty():
    paddle.seed(0)
    lin = nn.Linear(2, 2, bias_attr=False)
    w0 = lin.weight.numpy().copy()
    o = opt.SGD(learning_rate=0.5, parameters=lin.parameters(),
                weight_decay=paddle.regularizer.L1Decay(0.3))
    # zero data gradient: the update is ONLY the L1 penalty
    loss = (lin(paddle.to_tensor(np.zeros((1, 2), np.float32)))).sum()
    loss.backward()
    o.step()
    np.testing.assert_allclose(lin.weight.numpy(),
                               w0 - 0.5 * 0.3 * np.sign(w0), rtol=1e-6)


def test_adamw_rejects_regularizer():
    lin = nn.Linear(2, 2)
    with pytest.raises(TypeError):
        opt.AdamW(parameters=lin.parameters(),
                  weight_decay=paddle.regularizer.L2Decay(0.1))


# -------------------------------------------------------------------- signal

def test_stft_istft_round_trip():
    rng = np.random.default_rng(0)
    sig = rng.normal(size=(2, 2048)).astype(np.float32)
    x = paddle.to_tensor(sig)
    spec = paddle.signal.stft(x, n_fft=256, hop_length=64)
    rec = paddle.signal.istft(spec, n_fft=256, hop_length=64,
                              length=2048)
    np.testing.assert_allclose(rec.numpy(), sig, atol=2e-4)


def test_stft_istft_windowed_round_trip():
    rng = np.random.default_rng(1)
    sig = rng.normal(size=(1024,)).astype(np.float32)
    win = paddle.to_tensor(np.hanning(128).astype(np.float32))
    x = paddle.to_tensor(sig)
    spec = paddle.signal.stft(x, n_fft=128, hop_length=32, window=win)
    rec = paddle.signal.istft(spec, n_fft=128, hop_length=32, window=win,
                              length=1024)
    # hann + 75% overlap satisfies NOLA: interior reconstructs exactly
    np.testing.assert_allclose(rec.numpy()[64:-64], sig[64:-64], atol=2e-4)


# --------------------------------------------------------------- batch/reader

def test_batch_and_reader_toolkit():
    def r():
        return iter(range(10))

    out = list(paddle.batch(r, 3)())
    assert out == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    assert list(paddle.batch(r, 3, drop_last=True)()) == [
        [0, 1, 2], [3, 4, 5], [6, 7, 8]]

    from paddle_tpu import reader as R

    assert list(R.firstn(r, 4)()) == [0, 1, 2, 3]
    assert list(R.chain(r, r)()) == list(range(10)) * 2
    assert list(R.map_readers(lambda a, b: a + b, r, r)()) == [
        2 * i for i in range(10)]
    assert sorted(R.buffered(r, 2)()) == list(range(10))
    assert list(R.compose(r, r)()) == [(i, i) for i in range(10)]
    cached = R.cache(r)
    assert list(cached()) == list(range(10)) == list(cached())
    paddle.seed(3)
    shuffled = list(R.shuffle(r, 5)())
    assert sorted(shuffled) == list(range(10))
    mapped = list(R.xmap_readers(lambda s: s * s, r, 3, 4, order=True)())
    assert mapped == [i * i for i in range(10)]
    assert sorted(R.xmap_readers(lambda s: s + 1, r, 2, 4)()) == list(
        range(1, 11))
    assert sorted(R.multiprocess_reader([r, r])()) == sorted(
        list(range(10)) * 2)


# ----------------------------------------------------------------- callbacks

def test_reduce_lr_on_plateau():
    from paddle_tpu.callbacks import ReduceLROnPlateau

    class FakeModel:
        def __init__(self):
            self._optimizer = opt.SGD(
                learning_rate=1.0,
                parameters=nn.Linear(2, 2).parameters())

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    m = FakeModel()
    cb.set_model(m)
    cb.on_train_begin()
    losses = [1.0, 0.9, 0.9, 0.9, 0.9]
    for ep, lo in enumerate(losses):
        cb.on_epoch_end(ep, {"loss": lo})
    assert abs(m._optimizer.get_lr() - 0.5) < 1e-9  # one reduction fired


def test_callbacks_namespace_and_sysconfig():
    import paddle_tpu.callbacks as C

    for name in ("Callback", "ProgBarLogger", "ModelCheckpoint",
                 "EarlyStopping", "LRScheduler", "ReduceLROnPlateau",
                 "VisualDL"):
        assert hasattr(C, name)
    with pytest.raises(ImportError):
        C.VisualDL(log_dir="/tmp/x")
    assert paddle.sysconfig.get_include().endswith("include")
    assert paddle.sysconfig.get_lib().endswith("libs")


def test_reader_error_and_alignment_semantics():
    from paddle_tpu import reader as R

    def r10():
        return iter(range(10))

    def r5():
        return iter(range(5))

    def bad():
        def g():
            yield 1
            raise IOError("corrupt")
        return g()

    # misaligned compose raises under the default checking mode
    with pytest.raises(R.ComposeNotAligned):
        list(R.compose(r10, r5)())
    # unchecked mode truncates at the shortest
    assert list(R.compose(r10, r5, check_alignment=False)()) == [
        (i, i) for i in range(5)]
    # buffered propagates reader errors instead of truncating silently
    with pytest.raises(IOError):
        list(R.buffered(bad, 4)())
    # xmap surfaces mapper errors instead of deadlocking
    with pytest.raises(ZeroDivisionError):
        list(R.xmap_readers(lambda s: 1 // s, lambda: iter([1, 0, 2]),
                            2, 4)())


def test_per_param_regularizer_and_adamw_compose():
    from paddle_tpu.nn import ParamAttr

    # ParamAttr.regularizer reaches the Parameter and the optimizer
    lin = nn.Linear(2, 2, bias_attr=False,
                    weight_attr=ParamAttr(
                        regularizer=paddle.regularizer.L2Decay(0.3)))
    assert isinstance(lin.weight.regularizer, paddle.regularizer.L2Decay)
    w0 = lin.weight.numpy().copy()
    o = opt.SGD(learning_rate=0.5, parameters=lin.parameters())
    loss = (lin(paddle.to_tensor(np.zeros((1, 2), np.float32)))).sum()
    loss.backward()
    o.step()
    np.testing.assert_allclose(lin.weight.numpy(), w0 * (1 - 0.5 * 0.3),
                               rtol=1e-6)

    # under AdamW the per-param penalty COMPOSES with decoupled decay
    lin2 = nn.Linear(2, 2, bias_attr=False,
                     weight_attr=ParamAttr(
                         regularizer=paddle.regularizer.L2Decay(0.3)))
    ow = opt.AdamW(learning_rate=0.1, weight_decay=0.01,
                   parameters=lin2.parameters())
    w0 = lin2.weight.numpy().copy()
    loss = (lin2(paddle.to_tensor(np.zeros((1, 2), np.float32)))).sum()
    loss.backward()
    ow.step()
    # decoupled part: p -= lr * wd * p happens regardless; grad penalty
    # moves params further via the Adam moments — both active means the
    # result differs from decay-only AND from penalty-only updates
    decay_only = w0 * (1 - 0.1 * 0.01)
    assert not np.allclose(lin2.weight.numpy(), decay_only)
    assert not np.allclose(lin2.weight.numpy(), w0)


def test_version_module():
    import re

    import paddle_tpu.version as v

    assert re.fullmatch(r"\d+\.\d+\.\d+([a-z]+\d+)?", v.full_version)
    assert v.full_version.startswith(f"{v.major}.{v.minor}.")
    assert paddle.__version__ == v.full_version
    assert v.cuda() == "False" and v.cudnn() == "False"
    # a resolved commit is a full 40-char sha; anything else must be the
    # explicit Unknown fallback (no partial/garbled strings)
    assert v.commit == "Unknown" or re.fullmatch(r"[0-9a-f]{40}", v.commit)
    v.show()  # must not raise


def test_audio_wave_backend_round_trip(tmp_path):
    import paddle_tpu.audio as A

    sr = 16000
    t = np.arange(sr // 4) / sr
    sig = np.stack([np.sin(2 * np.pi * 440 * t),
                    np.sin(2 * np.pi * 220 * t)]).astype(np.float32)
    path = tmp_path / "tone.wav"
    A.save(str(path), paddle.to_tensor(sig), sr)

    meta = A.backends.info(str(path))
    assert (meta.sample_rate, meta.num_channels,
            meta.bits_per_sample) == (sr, 2, 16)
    out, sr2 = A.load(str(path))
    assert sr2 == sr and out.shape[0] == 2
    np.testing.assert_allclose(np.asarray(out.numpy()), sig, atol=1e-3)
    # raw int16 + frame windowing
    raw, _ = A.load(str(path), frame_offset=10, num_frames=100,
                    normalize=False)
    assert raw.numpy().dtype == np.int16 and raw.shape[1] == 100

    f = A.functional.fft_frequencies(16000, 512)
    assert f.shape[0] == 257 and float(f.numpy()[-1]) == 8000.0
    assert A.backends.get_current_backend() == "wave_backend"


def test_audio_backend_error_semantics(tmp_path):
    import io
    import wave as _wave

    import paddle_tpu.audio as A

    # non-16-bit wavs are rejected, not misread
    p8 = tmp_path / "pcm8.wav"
    with _wave.open(str(p8), "wb") as f:
        f.setnchannels(1)
        f.setsampwidth(1)
        f.setframerate(8000)
        f.writeframes(bytes(100))
    with pytest.raises(NotImplementedError):
        A.load(str(p8))
    # truncated garbage raises uniformly
    bad = tmp_path / "bad.wav"
    bad.write_bytes(b"RIFF")
    with pytest.raises(NotImplementedError):
        A.backends.info(str(bad))
    # caller-owned handles stay open
    p = tmp_path / "tone.wav"
    A.save(str(p), paddle.to_tensor(np.zeros((1, 64), np.float32)), 8000)
    h = open(p, "rb")
    A.backends.info(h)
    assert not h.closed
    h.close()
    # integer non-int16 input is rejected, not square-waved
    with pytest.raises(TypeError):
        A.save(str(p), np.array([[1000, -1000]], np.int32), 8000)
    # file-like save target works
    buf = io.BytesIO()
    A.save(buf, paddle.to_tensor(np.zeros((1, 64), np.float32)), 8000)
    buf.seek(0)
    out, sr = A.load(buf)
    assert sr == 8000 and out.shape == [1, 64]
