"""Serving resilience (paddle_tpu/resilience/ + scheduler hardening).

Chaos oracle: every run under a seeded ``FaultPlan`` must end with every
request in a terminal state (done/cancelled/failed/rejected), zero leaked
KV blocks, and — for requests that complete normally — token streams
bit-identical to the fault-free run (injection happens BEFORE dispatch
donates the cache, and ``allocator.extend`` is idempotent per position,
so a retried step rewrites identical KV). Plus: the degradation ladder's
ordered shed + hysteresis, the step-latency watchdog's StallStorm, the
truthful ``/healthz`` (ok -> degraded -> ok, and a dead driver thread
answering 503 instead of hanging), request validation, and the
serve_bench partial-artifact-on-death contract.
"""

import json
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.resilience import (
    DegradationLadder,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    LEVEL_OK,
    LEVEL_REJECT,
    LEVEL_SHRINK,
    StallStorm,
    StepWatchdog,
    classify_error,
    fault_plan,
    get_injector,
    inject,
)
from paddle_tpu.serving import (
    ContinuousBatchingScheduler,
    SchedulerConfig,
    SchedulerOverloaded,
)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts these decode programs' NUMERICS (wrong
    generated tokens) even when the persistent cache was written by the
    SAME jax build in the same session — the NOTES-r7 'stale cache' flake
    was this, and version-stamping the dir (utils/compile_cache.py) cannot
    catch a same-version unsound replay. Serving tests therefore compile
    fresh; the rest of the suite keeps the persistent-cache speedup."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _sched(model, **over):
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=8)
    kw.update(over)
    return ContinuousBatchingScheduler(model, SchedulerConfig(**kw))


def _prompts(n=4, seed=0, lo=4, hi=10):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 1000, int(rng.integers(lo, hi + 1)))
            for _ in range(n)]


def _drain(sched, guard=3000):
    while sched.has_unfinished():
        sched.step()
        guard -= 1
        assert guard > 0, "scheduler did not drain"
    return dict(sched._finished)


def _assert_pool_clean(sched):
    if sched.prefix_cache is not None:
        sched.prefix_cache.flush()
    assert sched.allocator.num_used_blocks == 0, (
        f"block leak: {sched.allocator.num_used_blocks} blocks still held "
        f"after drain")


# ------------------------------------------------------- fault plan units

def test_fault_plan_fires_at_exact_hits():
    inj = FaultInjector()
    inj.arm(FaultPlan(seed=0).on("serving.decode_step", at=(2, 4)))
    fired = []
    for i in range(1, 6):
        try:
            inj.check("serving.decode_step")
            fired.append(False)
        except InjectedFault as e:
            fired.append(True)
            assert e.site == "serving.decode_step" and e.hit == i
    assert fired == [False, True, False, True, False]
    snap = inj.snapshot()
    assert snap["hits"]["serving.decode_step"] == 5
    assert snap["fires"]["serving.decode_step"] == 2


def test_fault_plan_probability_is_seed_deterministic():
    def pattern(seed):
        inj = FaultInjector()
        inj.arm(FaultPlan(seed=seed).on("serving.decode_step", prob=0.5))
        out = []
        for _ in range(32):
            try:
                inj.check("serving.decode_step")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    assert pattern(3) == pattern(3)          # same seed, same firing order
    assert pattern(3) != pattern(4)
    assert 0 < sum(pattern(3)) < 32


def test_fault_plan_times_caps_total_fires():
    inj = FaultInjector()
    inj.arm(FaultPlan(seed=0).on("serving.decode_step", prob=1.0, times=2))
    fires = 0
    for _ in range(10):
        try:
            inj.check("serving.decode_step")
        except InjectedFault:
            fires += 1
    assert fires == 2


def test_unknown_site_rejected():
    with pytest.raises(ValueError, match="unknown injection site"):
        FaultPlan(seed=0).on("serving.nope", prob=1.0)


def test_disarmed_inject_is_inert():
    assert not get_injector().armed
    for _ in range(3):
        inject("serving.decode_step")        # must not raise or count
    assert get_injector().snapshot()["armed"] is False


def test_classify_error():
    assert classify_error(InjectedFault("s", 1, kind="fatal")) == "fatal"
    assert classify_error(InjectedFault("s", 1)) == "transient"
    assert classify_error(ValueError("bad")) == "fatal"
    assert classify_error(OSError("io")) == "transient"


# --------------------------------- per-site recovery with token identity

@pytest.mark.parametrize("site,rule", [
    ("serving.decode_step", dict(at=(2, 5))),
    ("serving.prefill", dict(at=1)),
    ("serving.block_alloc", dict(at=(1, 3))),
])
def test_transient_fault_recovers_token_identical(model, site, rule):
    prompts = _prompts(4)
    base_sched = _sched(model)
    base_rids = [base_sched.add_request(p, max_new_tokens=5)
                 for p in prompts]
    base = _drain(base_sched)

    sched = _sched(model)
    rids = [sched.add_request(p, max_new_tokens=5) for p in prompts]
    with fault_plan(FaultPlan(seed=0).on(site, **rule)):
        outs = _drain(sched)
        assert get_injector().snapshot()["fires"].get(site, 0) >= 1
    for r0, r1 in zip(base_rids, rids):
        assert outs[r1].finish_reason in ("length", "eos")
        np.testing.assert_array_equal(base[r0].token_ids,
                                      outs[r1].token_ids)
    _assert_pool_clean(sched)
    assert any("fired" in k and site in k
               for k in sched.metrics.faults_snapshot())


def test_prefix_insert_fault_is_best_effort(model):
    rng = np.random.default_rng(5)
    shared = rng.integers(0, 1000, 16)
    prompts = [np.concatenate([shared, rng.integers(0, 1000, 4)])
               for _ in range(3)]
    base_sched = _sched(model, enable_prefix_caching=True)
    base_rids = [base_sched.add_request(p, max_new_tokens=4)
                 for p in prompts]
    base = _drain(base_sched)

    sched = _sched(model, enable_prefix_caching=True)
    rids = [sched.add_request(p, max_new_tokens=4) for p in prompts]
    with fault_plan(FaultPlan(seed=0).on("serving.prefix_insert",
                                         prob=1.0)):
        outs = _drain(sched)
    # inserts were skipped, not fatal: generation identical, nothing leaks
    for r0, r1 in zip(base_rids, rids):
        np.testing.assert_array_equal(base[r0].token_ids,
                                      outs[r1].token_ids)
    _assert_pool_clean(sched)


def test_weight_reload_fault_leaves_weights_intact(model, tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, model=model)
    prompt = _prompts(1)[0]

    base_sched = _sched(model)
    r0 = base_sched.add_request(prompt, max_new_tokens=5)
    base = _drain(base_sched)

    sched = _sched(model)
    with fault_plan(FaultPlan(seed=0).on("serving.weight_reload", at=1)):
        with pytest.raises(InjectedFault):
            sched.reload_weights(mgr)
    # the fault fired before restore touched the model: serving continues
    # on the old weights, token-identical
    r1 = sched.add_request(prompt, max_new_tokens=5)
    outs = _drain(sched)
    np.testing.assert_array_equal(base[r0].token_ids, outs[r1].token_ids)
    assert any("serving.weight_reload" in k
               for k in sched.metrics.faults_snapshot())


def test_fault_budget_exhaustion_fails_request(model):
    sched = _sched(model, max_step_faults=3)
    rid = sched.add_request(_prompts(1)[0], max_new_tokens=5)
    with fault_plan(FaultPlan(seed=0).on("serving.decode_step", prob=1.0)):
        outs = _drain(sched)
    assert outs[rid].finish_reason == "failed"
    assert sched.metrics.requests_failed == 1
    assert any("request_failed" in k
               for k in sched.metrics.faults_snapshot())
    _assert_pool_clean(sched)


def test_all_sites_chaos_peers_identical_and_zero_leak(model):
    prompts = _prompts(6, seed=2)
    base_sched = _sched(model, enable_prefix_caching=True)
    base_rids = [base_sched.add_request(p, max_new_tokens=5)
                 for p in prompts]
    base = _drain(base_sched)

    plan = FaultPlan(seed=1)
    for site in ("serving.decode_step", "serving.prefill",
                 "serving.block_alloc", "serving.prefix_insert"):
        plan.on(site, prob=0.2)
    sched = _sched(model, enable_prefix_caching=True, max_step_faults=2)
    rids = [sched.add_request(p, max_new_tokens=5) for p in prompts]
    with fault_plan(plan):
        outs = _drain(sched)
    assert len(outs) == len(prompts)         # no fault may leak a request
    for r0, r1 in zip(base_rids, rids):
        assert outs[r1].finish_reason in ("length", "eos", "failed")
        if outs[r1].finish_reason != "failed":
            # peers that survived the storm are bit-identical
            np.testing.assert_array_equal(base[r0].token_ids,
                                          outs[r1].token_ids)
    _assert_pool_clean(sched)


# --------------------------------------------- cancellation and deadlines

def test_cancel_queued_running_idempotent_unknown(model):
    sched = _sched(model, max_num_seqs=1)
    p1, p2 = _prompts(2)
    r1 = sched.add_request(p1, max_new_tokens=8)
    r2 = sched.add_request(p2, max_new_tokens=8)
    sched.step()                             # r1 running, r2 queued
    out2 = sched.cancel(r2)                  # queued: freed off-grid
    assert out2.finish_reason == "cancelled"
    assert len(out2.generated_ids) == 0
    out1 = sched.cancel(r1)                  # running: slot + blocks freed
    assert out1.finish_reason == "cancelled"
    assert len(out1.generated_ids) >= 1
    assert sched.cancel(r1).finish_reason == "cancelled"   # idempotent
    with pytest.raises(KeyError):
        sched.cancel(10 ** 9)
    assert not sched.has_unfinished()
    _assert_pool_clean(sched)
    assert sched.metrics.cancelled_snapshot() == {'cause="user"': 2.0}


def test_deadline_cancels_with_reason_deadline(model):
    sched = _sched(model, max_num_seqs=1)
    r1 = sched.add_request(_prompts(1)[0], max_new_tokens=50,
                           deadline_s=1e-6)
    outs = _drain(sched)
    assert outs[r1].finish_reason == "deadline"
    assert any('cause="deadline"' in k
               for k in sched.metrics.cancelled_snapshot())
    _assert_pool_clean(sched)


def test_queue_ttl_evicts_stale_queued_only(model):
    sched = _sched(model, max_num_seqs=1, queue_ttl_s=0.05)
    p1, p2 = _prompts(2)
    r1 = sched.add_request(p1, max_new_tokens=4)
    r2 = sched.add_request(p2, max_new_tokens=4)
    sched.step()                             # r1 admitted before the TTL
    time.sleep(0.1)
    outs = _drain(sched)
    assert outs[r1].finish_reason in ("length", "eos")   # running: immune
    assert outs[r2].finish_reason == "queue_ttl"
    assert any('cause="queue_ttl"' in k
               for k in sched.metrics.cancelled_snapshot())
    _assert_pool_clean(sched)


# ------------------------------------------- degradation ladder + watchdog

def test_ladder_escalates_immediately_deescalates_with_hysteresis():
    lad = DegradationLadder(flush_at=0.5, shrink_at=0.7, reject_at=0.9,
                            recover_at=0.3, cooldown_steps=2)
    assert lad.observe(0.95) == (0, 3)       # spike: straight to reject
    assert lad.state == "reject"
    assert lad.observe(0.1) == (3, 3)        # calm 1: holds (hysteresis)
    assert lad.observe(0.1) == (3, 2)        # calm 2: one rung down
    assert lad.observe(0.4) == (2, 2)        # not calm enough: resets
    assert lad.observe(0.1) == (2, 2)
    assert lad.observe(0.1) == (2, 1)
    assert lad.observe(0.1)[1] == 1
    assert lad.observe(0.1) == (1, 0)
    assert lad.state == "ok" and lad.transitions == 4
    with pytest.raises(ValueError, match="thresholds"):
        DegradationLadder(flush_at=0.5, shrink_at=0.4)


def test_step_watchdog_fires_stall_storm_once_per_streak():
    wd = StepWatchdog(factor=3.0, min_history=4, streak=2)
    for _ in range(8):
        assert not wd.observe(0.01)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert wd.observe(1.0)
        assert wd.observe(1.0)               # streak of 2 -> one storm
        assert wd.observe(0.01) is False     # recovery resets the run
    storms = [x for x in w if isinstance(x.message, StallStorm)]
    assert len(storms) == 1
    assert wd.storms == 1 and wd.slow_steps == 2
    # slow samples were not folded into the EWMA
    assert wd.ewma == pytest.approx(0.01, rel=0.01)


def test_degradation_engages_under_queue_pressure_and_recovers(model):
    sched = _sched(model, max_num_seqs=1, max_queue_size=4,
                   shed_flush_occupancy=0.5, shed_shrink_occupancy=0.9,
                   shed_reject_occupancy=0.95, shed_recover_occupancy=0.3,
                   shed_cooldown_steps=1)
    for p in _prompts(4, seed=3):
        sched.add_request(p, max_new_tokens=3)
    sched.step()                             # queue 3/4 = 0.75 -> degraded
    assert sched.health()["state"] == "degraded"
    assert sched.metrics.snapshot()["degradation_level"] >= 1
    _drain(sched)
    for _ in range(4):                       # calm steps de-escalate
        sched.step()
    assert sched.health()["state"] == "ok"
    assert sched.metrics.snapshot()["degradation_level"] == 0
    assert sched._ladder.transitions >= 2


def test_warm_prefix_cache_is_not_pool_pressure(model):
    # A pool full of evictable cached blocks must neither hold the shed
    # ladder up nor gate admission: the tree's blocks are reclaimed by the
    # very allocate() call an admission makes, so they are not load. Before
    # the _pool_pressure() fix this livelocked — gated admission never
    # allocates, and allocation is the only eviction trigger.
    sched = _sched(model, enable_prefix_caching=True, num_blocks=12,
                   shed_flush_occupancy=0.6, shed_shrink_occupancy=0.7,
                   shed_reject_occupancy=0.99, shed_recover_occupancy=0.3,
                   shed_cooldown_steps=1)
    for p in _prompts(6, seed=11, lo=12, hi=17):
        sched.add_request(p, max_new_tokens=3)
    _drain(sched)                           # retires warm the radix tree
    assert sched.prefix_cache.reclaimable_blocks() > 0
    raw = sched.allocator.utilization()
    live = sched._pool_pressure()
    assert live < 0.3 <= raw, (live, raw)   # warm cache, no live load
    sched._ladder.observe(0.8)              # pressure spike -> SHRINK
    assert sched._ladder.level >= LEVEL_SHRINK
    for p in _prompts(4, seed=12, lo=12, hi=17):
        sched.add_request(p, max_new_tokens=3)
    outs = _drain(sched)                    # hung here before the fix
    assert len(outs) == 10
    for _ in range(4):                      # calm steps de-escalate
        sched.step()
    assert sched.health()["state"] == "ok"
    _assert_pool_clean(sched)


def test_overload_rejection_at_reject_level_and_while_draining(model):
    sched = _sched(model)
    sched._ladder.observe(1.0)               # pressure spike -> reject
    assert sched._ladder.level == LEVEL_REJECT
    with pytest.raises(SchedulerOverloaded, match="overloaded"):
        sched.add_request(_prompts(1)[0], max_new_tokens=3)
    while sched._ladder.level > LEVEL_OK:
        sched._ladder.observe(0.0)
    sched.start_drain()
    with pytest.raises(SchedulerOverloaded, match="draining"):
        sched.add_request(_prompts(1)[0], max_new_tokens=3)
    assert sched.metrics.snapshot()["requests_rejected"] == 2
    assert sched.health()["state"] == "draining"


# ------------------------------------------------- /healthz truthfulness

def test_healthz_flips_ok_degraded_ok_and_dead_driver_is_503(model):
    sched = _sched(model, shed_cooldown_steps=1)
    ep = sched.start_endpoint()
    try:
        def healthz():
            return urllib.request.urlopen(ep.url + "/healthz",
                                          timeout=10).read()

        assert healthz() == b"ok"
        sched._ladder.observe(1.0)
        assert healthz() == b"degraded"      # degraded is alive: still 200
        for _ in range(6):
            sched._ladder.observe(0.0)
        assert healthz() == b"ok"

        # a dead scheduler thread with work pending must answer 503, not
        # hang the probe or lie "ok"
        sched.add_request(_prompts(1)[0], max_new_tokens=3)
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        sched.attach_driver(t)
        with pytest.raises(urllib.error.HTTPError) as ei:
            healthz()
        assert ei.value.code == 503
        assert ei.value.read() == b"dead"
    finally:
        ep.stop()
    _drain(sched)                            # leave the module-scoped pool


# --------------------------------------------------- add_request validation

def test_add_request_validation(model):
    sched = _sched(model)
    with pytest.raises(ValueError, match="at least one token"):
        sched.add_request(np.array([], dtype=np.int64), max_new_tokens=3)
    with pytest.raises(ValueError, match="integer token ids"):
        sched.add_request(np.array([1.0, 2.0]), max_new_tokens=3)
    with pytest.raises(ValueError, match="max_new_tokens"):
        sched.add_request(np.array([1, 2]), max_new_tokens=0)
    with pytest.raises(ValueError, match="largest prefill bucket"):
        sched.add_request(np.arange(200), max_new_tokens=3)
    with pytest.raises(ValueError, match="deadline_s"):
        sched.add_request(np.array([1, 2]), max_new_tokens=3,
                          deadline_s=0.0)
    assert not sched.has_unfinished()
    assert sched.metrics.snapshot()["requests_received"] == 0


# ----------------------------------------------------- serve_bench chaos

def test_chaos_load_census_and_zero_leak():
    from tools.serve_bench import run_chaos_load

    art = run_chaos_load(num_requests=5, rate=1.0, seed=0,
                         fault_rate=0.3, cancel_rate=0.3,
                         new_tokens=(3, 5), max_step_faults=2)
    terminal = set(art["census"]) | {"rejected"}
    assert terminal <= {"length", "eos", "cancelled", "failed", "rejected"}
    assert sum(art["census"].values()) + art["rejected"] == 5
    assert not get_injector().armed          # the bench disarms on exit


def test_serve_bench_writes_partial_artifact_on_death(tmp_path,
                                                      monkeypatch):
    import tools.serve_bench as sb

    def boom(**kw):
        raise RuntimeError("mid-bench death")

    monkeypatch.setattr(sb, "run_load", boom)
    out = tmp_path / "BENCH_dead.json"
    with pytest.raises(RuntimeError, match="mid-bench death"):
        sb.main(["--smoke", "--out", str(out)])
    art = json.loads(out.read_text())
    assert art["completed"] is False
    assert "RuntimeError: mid-bench death" in art["error"]
    assert art["bench"] == "serving_smoke" and art["config"]["smoke"]
