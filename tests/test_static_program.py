"""Imperative static-graph building (VERDICT r3 missing #5): a CLASSIC
paddle static script — enable_static, program_guard, static.data,
static.nn.fc, optimizer.minimize, Executor.run(feed, fetch_list) — runs
unmodified. Reference: base/framework.py Program:5810 +
base/executor.py Executor:1179."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _toy_data(n=64, din=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, din)).astype(np.float32)
    W = rng.normal(size=(din, classes)).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.int64).reshape(n, 1)
    return X, y


def test_classic_static_train_script(static_mode):
    """The canonical static MNIST-style script, end to end."""
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 16], "float32")
        y = static.data("y", [None, 1], "int64")
        hidden = static.nn.fc(x, 32, activation="relu")
        logits = static.nn.fc(hidden, 4)
        loss = F.cross_entropy(logits, y)
        avg = paddle.mean(loss)
        opt = paddle.optimizer.SGD(learning_rate=0.5)
        opt.minimize(avg)

    exe = static.Executor(paddle.CPUPlace())
    exe.run(startup)

    X, Y = _toy_data()
    losses = []
    for _ in range(20):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg])
        losses.append(float(lv))
    assert losses[-1] < 0.5 * losses[0], losses
    assert np.isfinite(losses).all()


def test_static_matches_dygraph_forward(static_mode):
    """Same weights -> identical forward between the imperative program
    and a dygraph computation."""
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        out = static.nn.fc(x, 3)

    exe = static.Executor()
    exe.run(startup)
    X = np.random.default_rng(1).normal(size=(5, 8)).astype(np.float32)
    (got,) = exe.run(main, feed={"x": X}, fetch_list=[out])

    w = np.asarray(main.scope[main.params[0].name])
    b = np.asarray(main.scope[main.params[1].name])
    np.testing.assert_allclose(got, X @ w + b, rtol=1e-5, atol=1e-6)


def test_static_eval_clone_shares_weights(static_mode):
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        out = static.nn.fc(x, 2)
        y = static.data("y", [None, 2], "float32")
        avg = paddle.mean((out - y) * (out - y))
        paddle.optimizer.SGD(learning_rate=0.1).minimize(avg)

    test_prog = main.clone(for_test=True)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.default_rng(2)
    X = rng.normal(size=(4, 8)).astype(np.float32)
    Y = rng.normal(size=(4, 2)).astype(np.float32)
    (l0,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg])
    # eval clone: no optimizer -> params unchanged, loss reflects training
    (le,) = exe.run(test_prog, feed={"x": X, "y": Y}, fetch_list=[avg])
    (l1,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg])
    np.testing.assert_allclose(le, l1, rtol=1e-5)
    assert float(l1) < float(l0)


def test_data_returns_inputspec_in_dygraph():
    spec = static.data("x", [None, 4], "float32")
    assert isinstance(spec, static.InputSpec)


def test_variable_arithmetic_and_mixed_constants(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        z = (x * 2.0 + 1.0) / 2.0 - x
        out = paddle.mean(z)
    exe = static.Executor()
    X = np.random.default_rng(3).normal(size=(3, 4)).astype(np.float32)
    (got,) = exe.run(main, feed={"x": X}, fetch_list=[out])
    np.testing.assert_allclose(got, np.mean((X * 2 + 1) / 2 - X),
                               rtol=1e-6)


def test_static_lr_is_runtime_not_baked(static_mode):
    """Review r4: set_lr after the first run must take effect (the lr is a
    runner argument, not a constant baked into the compiled program)."""
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        out = static.nn.fc(x, 1)
        avg = paddle.mean((out - y) * (out - y))
        opt = paddle.optimizer.SGD(learning_rate=0.0)
        opt.minimize(avg)

    exe = static.Executor()
    exe.run(startup)
    rng = np.random.default_rng(4)
    X = rng.normal(size=(8, 4)).astype(np.float32)
    Y = rng.normal(size=(8, 1)).astype(np.float32)
    w_name = main.params[0].name
    exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg])
    w0 = np.asarray(main.scope[w_name]).copy()
    np.testing.assert_allclose(w0, np.asarray(main.scope[w_name]))  # lr 0
    opt.set_lr(0.5)
    exe.run(main, feed={"x": X, "y": Y}, fetch_list=[avg])
    assert not np.allclose(w0, np.asarray(main.scope[w_name]))
