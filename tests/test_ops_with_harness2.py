"""Second batch of OpTest-harness op tests (conv/pool/norm/embedding/
reduction/index families)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from tests.op_test import OpTest

rng = np.random.default_rng(7)


class TestConv2DOp(OpTest):
    op = staticmethod(F.conv2d)
    attrs = {"stride": 1, "padding": 1}
    inputs = {
        "x": rng.standard_normal((2, 3, 8, 8)).astype(np.float32),
        "weight": rng.standard_normal((4, 3, 3, 3)).astype(np.float32) * 0.2,
    }

    @staticmethod
    def ref(x, weight, stride, padding):
        assert stride == 1  # ref only covers the unit-stride case
        N, C, H, W = x.shape
        O, _, kh, kw = weight.shape
        xp = np.pad(x, ((0, 0), (0, 0), (padding, padding),
                        (padding, padding)))
        out = np.zeros((N, O, H, W), np.float32)
        for i in range(H):
            for j in range(W):
                patch = xp[:, :, i:i + kh, j:j + kw]
                out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, weight)
        return out

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-4)
        self.check_grad(["weight"], rtol=3e-2, atol=3e-2, eps=1e-2)


class TestMaxPoolOp(OpTest):
    op = staticmethod(F.max_pool2d)
    attrs = {"kernel_size": 2, "stride": 2}
    inputs = {"x": rng.standard_normal((1, 2, 4, 4)).astype(np.float32)}

    @staticmethod
    def ref(x, kernel_size, stride):
        assert kernel_size == stride  # ref only covers the tiled case
        k = kernel_size
        N, C, H, W = x.shape
        return x.reshape(N, C, H // k, k, W // k, k).max((3, 5))

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestEmbeddingOp(OpTest):
    op = staticmethod(F.embedding)
    attrs = {}
    inputs = {
        "x": np.array([1, 0, 3], np.int64),
        "weight": rng.standard_normal((5, 4)).astype(np.float32),
    }

    @staticmethod
    def ref(x, weight):
        return weight[x]

    def test(self):
        self.check_output()
        self.check_grad(["weight"])


class TestSiluOp(OpTest):
    op = staticmethod(F.silu)
    attrs = {}
    inputs = {"x": rng.standard_normal((6,)).astype(np.float32)}

    @staticmethod
    def ref(x):
        return x / (1 + np.exp(-x))

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)
        self.check_grad(["x"])


class TestMeanOp(OpTest):
    op = staticmethod(paddle.mean)
    attrs = {"axis": 1, "keepdim": True}
    inputs = {"x": rng.standard_normal((3, 5)).astype(np.float32)}

    @staticmethod
    def ref(x, axis, keepdim):
        return x.mean(axis=axis, keepdims=keepdim)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestVarOp(OpTest):
    op = staticmethod(paddle.var)
    attrs = {"axis": 0}
    inputs = {"x": rng.standard_normal((6, 3)).astype(np.float32)}

    @staticmethod
    def ref(x, axis):
        return x.var(axis=axis, ddof=1)  # paddle defaults to unbiased

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["x"])


class TestClipOp(OpTest):
    op = staticmethod(paddle.clip)
    attrs = {"min": -0.5, "max": 0.5}
    inputs = {"x": rng.standard_normal((8,)).astype(np.float32)}

    @staticmethod
    def ref(x, min, max):
        return np.clip(x, min, max)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestIndexSelectOp(OpTest):
    op = staticmethod(paddle.index_select)
    attrs = {"axis": 1}
    inputs = {
        "x": rng.standard_normal((3, 6)).astype(np.float32),
        "index": np.array([0, 5, 2], np.int64),
    }

    @staticmethod
    def ref(x, index, axis):
        return np.take(x, index, axis=axis)

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestPowOp(OpTest):
    op = staticmethod(paddle.pow)
    attrs = {"y": 3.0}
    # strictly positive base: independent of global rng consumption order
    inputs = {"x": (np.abs(np.random.default_rng(11).standard_normal(5))
                    + 0.5).astype(np.float32)}

    @staticmethod
    def ref(x, y):
        return x ** y

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-4)
        self.check_grad(["x"], rtol=2e-2)


class TestSoftmaxWithCEOp(OpTest):
    op = staticmethod(F.softmax_with_cross_entropy)
    attrs = {}
    inputs = {
        "logits": rng.standard_normal((4, 6)).astype(np.float32),
        "label": rng.integers(0, 6, (4, 1)).astype(np.int64),
    }

    @staticmethod
    def ref(logits, label):
        m = logits.max(-1, keepdims=True)
        lse = np.log(np.exp(logits - m).sum(-1, keepdims=True)) + m
        return lse - np.take_along_axis(logits, label, axis=-1)

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["logits"])  # harness default handles tuple outputs
