"""Chunked prefill + speculative decoding (paddle_tpu/serving/spec/).

The contract under test: both features are pure LATENCY-SHAPE changes —
token streams bit-identical to the depth-0 unchunked autoregressive
oracle through every composition (dispatch depth, tensor parallelism,
forced preemption mid-prefill, prefix-cache eviction, router failover
with an in-flight chunk frontier) — while the engine keeps its
zero-steady-state-recompile invariant over the enlarged program set
(decode grid + chunk program + verify grid).

Runs on the emulated CPU mesh (conftest forces
--xla_force_host_platform_device_count=8). Repetitive prompts are the
n-gram proposer's favorable regime — the spec legs exercise REAL accepts,
not just the fallback path.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ContinuousBatchingScheduler,
    SchedulerConfig,
    ServingRouter,
)
from paddle_tpu.serving.sharded import DeviceGroupPlan, TensorParallelSharding
from paddle_tpu.serving.spec import NgramProposer, Proposer


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts decode-program numerics (see
    test_serving_async.py) — serving tests compile fresh."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _sched(depth=0, tp=None, chunk=0, k=0, **over):
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=8,
              dispatch_depth=depth, prefill_chunk_size=chunk, spec_k=k)
    kw.update(over)
    sharding = TensorParallelSharding(tp=tp) if tp else None
    return ContinuousBatchingScheduler(_model(), SchedulerConfig(**kw),
                                       sharding=sharding)


def _prompts(n, seed=0):
    """Half repetitive (real n-gram accepts), half random (fallback +
    low-accept verify) — the identity oracle must hold over both."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        if i % 2 == 0:
            pat = rng.integers(2, 40, 6)
            out.append(np.concatenate([pat, pat]))
        else:
            out.append(rng.integers(0, 1000, int(rng.integers(5, 13))))
    return out


def _pool_clean(sched):
    if sched.prefix_cache is not None:
        sched.prefix_cache.flush()
    assert sched.allocator.num_used_blocks == 0, (
        f"block leak: {sched.allocator.num_used_blocks} still held")


# ------------------------------------------------------ proposer (host)

def test_ngram_proposer_longest_recent_suffix():
    p = NgramProposer(max_n=3, min_n=1)
    assert isinstance(p, Proposer)
    # suffix (7, 8) occurred earlier; the follower run is proposed
    ctx = np.array([7, 8, 9, 1, 7, 8])
    np.testing.assert_array_equal(p.propose(ctx, 3), [9, 1, 7])
    # most RECENT earlier occurrence wins over the first one
    ctx = np.array([5, 1, 5, 2, 5])
    np.testing.assert_array_equal(p.propose(ctx, 1), [2])
    # proposal clamped to what actually follows the match
    np.testing.assert_array_equal(p.propose(np.array([3, 4, 3]), 5), [4, 3])


def test_ngram_proposer_declines_and_validates():
    p = NgramProposer(max_n=3, min_n=1)
    assert p.propose(np.array([1, 2, 3, 4]), 4) is None   # no repeats
    assert p.propose(np.array([5]), 2) is None            # too short
    assert p.propose(np.array([1, 2, 1, 3]), 0) is None   # k < 1
    with pytest.raises(ValueError, match="min_n"):
        NgramProposer(max_n=1, min_n=2)
    with pytest.raises(ValueError, match="min_n"):
        NgramProposer(max_n=2, min_n=0)


def test_greedy_only_gate():
    for kw in (dict(chunk=16), dict(k=3)):
        with pytest.raises(ValueError, match="greedy"):
            _sched(temperature=0.7, **kw)


# ------------------------------------------------------- identity oracle

def test_chunked_and_spec_match_oracle_every_depth_and_tp():
    """feature in {chunked, spec, both} x depth {0, 2}, plus both at
    tp=2: token streams bit-identical to the depth-0 unchunked oracle."""
    prompts = _prompts(4)
    oracle = _sched()
    refs = oracle.generate(prompts, max_new_tokens=6)
    oracle.shutdown()
    cases = [dict(chunk=8), dict(k=3), dict(chunk=8, k=3)]
    for case in cases:
        for depth in (0, 2):
            sched = _sched(depth=depth, **case)
            outs = sched.generate(prompts, max_new_tokens=6)
            for o, ref in zip(outs, refs):
                np.testing.assert_array_equal(
                    o, ref, err_msg=f"{case} depth={depth}")
            sched.shutdown()
            _pool_clean(sched)
    for tp in (1, 2):
        sched = _sched(tp=tp, chunk=8, k=3)
        outs = sched.generate(prompts, max_new_tokens=6)
        for o, ref in zip(outs, refs):
            np.testing.assert_array_equal(o, ref, err_msg=f"tp={tp}")
        sched.shutdown()
        _pool_clean(sched)


def test_spec_eos_and_budget_identical():
    """Early EOS inside an accepted run and a tight max_new budget must
    truncate the spec emit exactly like the autoregressive engine."""
    prompts = _prompts(2)
    base = _sched()
    refs = base.generate(prompts, max_new_tokens=6)
    base.shutdown()
    # an eos the oracle actually emits mid-stream -> real early stop
    eos = int(refs[0][len(prompts[0]) + 2])
    ref_eos = None
    for kw in (dict(), dict(chunk=8, k=4)):
        sched = _sched(**kw)
        outs = sched.generate(prompts, max_new_tokens=6, eos_token_id=eos)
        if ref_eos is None:
            ref_eos = outs
            assert any(len(o) < len(r) for o, r in zip(outs, refs)), (
                "chosen eos did not actually stop any stream early")
        else:
            for o, r in zip(outs, ref_eos):
                np.testing.assert_array_equal(o, r)
        sched.shutdown()
        _pool_clean(sched)
    # budget tighter than the draft depth: never emit past max_new
    sched = _sched(k=4)
    outs = sched.generate(prompts, max_new_tokens=2)
    for o, p, r in zip(outs, prompts, refs):
        assert len(o) == len(p) + 2
        np.testing.assert_array_equal(o, r[:len(o)])
    sched.shutdown()
    _pool_clean(sched)


def test_preemption_mid_prefill_identical():
    """Pool sized so the chunked engine preempts while long prompts are
    still mid-prefill: the frontier is dropped, blocks freed, and the
    recompute-resume stays token-identical to the unchunked engine."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1000, 10) for _ in range(2)]
    ref, preempted = None, 0
    for chunk, k in ((0, 0), (4, 0), (4, 3)):
        sched = _sched(chunk=chunk, k=k, block_size=4, num_blocks=6)
        outs = sched.generate(prompts, max_new_tokens=8)
        if chunk:
            preempted += sched.metrics.snapshot()["preemptions"]
        if ref is None:
            ref = outs
        else:
            for a, b in zip(ref, outs):
                np.testing.assert_array_equal(a, b)
        sched.shutdown()
        _pool_clean(sched)
    assert preempted >= 1, "pool never forced a preemption under chunking"


def test_prefix_cache_eviction_chunked_identical():
    """Identity must survive prefix caching with continuous LRU eviction
    while chunking + speculation are on."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 1000, int(k))
               for k in rng.integers(9, 20, 6)]
    ref = None
    for kw in (dict(), dict(chunk=8, k=3)):
        sched = _sched(enable_prefix_caching=True, num_blocks=8, **kw)
        outs = sched.generate(prompts, max_new_tokens=5)
        assert sched.prefix_cache_stats()["evicted_blocks"] > 0
        if ref is None:
            ref = outs
        else:
            for a, b in zip(ref, outs):
                np.testing.assert_array_equal(a, b)
        sched.shutdown()
        _pool_clean(sched)


def test_chunked_prefill_skips_cached_prefix():
    """A repeat prompt's cached prefix is NOT re-chunked: the chunk
    frontier starts at the radix match, so the second admission prefills
    strictly fewer tokens — token streams identical both times."""
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 1000, 40)
    sched = _sched(chunk=8, k=3, enable_prefix_caching=True)
    out1 = sched.generate([prompt], max_new_tokens=4)[0]
    first = sched.metrics.snapshot()["prefill_tokens"]
    out2 = sched.generate([prompt], max_new_tokens=4)[0]
    second = sched.metrics.snapshot()["prefill_tokens"] - first
    np.testing.assert_array_equal(out1, out2)
    assert sched.prefix_cache_stats()["hit_tokens"] > 0
    assert 0 < second < first, (
        f"cached prefix was re-chunked: {second} vs {first} prefilled")
    sched.shutdown()
    _pool_clean(sched)


# -------------------------------------------- failover: chunk frontier

def test_export_restartable_mid_prefill_frontier():
    """Export while a request is mid-chunked-prefill: the spec carries
    the chunk frontier, the pool is leak-free, and replaying on a fresh
    engine is token-identical."""
    rng = np.random.default_rng(5)
    long_prompt = rng.integers(0, 1000, 40)
    oracle = _sched(max_seq_len=64)
    ref = oracle.generate([long_prompt], max_new_tokens=5)[0]
    oracle.shutdown()

    src = _sched(chunk=8, k=3, max_seq_len=64)
    rid = src.add_request(long_prompt, max_new_tokens=5)
    src.step()                      # admission packs the slot mid-prefill
    specs = src.export_restartable()
    assert src.allocator.num_used_blocks == 0
    [spec] = specs
    assert spec["request_id"] == rid
    assert spec["prefill_pos"] >= 0, (
        "exported mid-prefill request must carry its chunk frontier")
    assert spec["prefill_pos"] < len(long_prompt)

    dst = _sched(chunk=8, k=3, max_seq_len=64)
    new_rid = dst.import_resumed(spec)
    guard = 2000
    while dst.has_unfinished():
        dst.step()
        guard -= 1
        assert guard > 0
    np.testing.assert_array_equal(dst._finished[new_rid].token_ids, ref)
    dst.shutdown()
    _pool_clean(dst)
    src.shutdown()


def test_router_kill_drill_with_chunk_frontier():
    """Crash a replica while a long prompt's chunk frontier is in flight:
    every request completes on the survivor bit-identical to the
    oracle."""
    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, 1000, 40)] + _prompts(3, seed=6)
    oracle = _sched()
    orids = [oracle.add_request(p, max_new_tokens=5) for p in prompts]
    guard = 3000
    while oracle.has_unfinished():
        oracle.step()
        guard -= 1
        assert guard > 0
    refs = [oracle._finished[r].token_ids for r in orids]
    oracle.shutdown()

    def make_replica(sh):
        return ContinuousBatchingScheduler(
            _model(), SchedulerConfig(max_num_seqs=2, max_seq_len=64,
                                      block_size=8, prefill_chunk_size=8,
                                      spec_k=3),
            sharding=sh)

    plan = DeviceGroupPlan(tp=1, replicas=2)
    router = ServingRouter(plan.replica_factories(make_replica),
                           cooldown_s=0.05, device_ownership="error")
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    router.step()                   # admissions land; frontiers open
    router.crash_replica(0)
    outs = {}
    guard = 3000
    while len(outs) < len(rids):
        for o in router.step():
            outs[o.request_id] = o
        guard -= 1
        assert guard > 0
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(outs[rid].token_ids, ref)
    router.shutdown()


# ------------------------------------------------- compiled-program pins

def test_zero_steady_state_recompiles_both_features():
    """With chunking AND speculation on, the program set is exactly
    {decode grid, chunk program, verify grid} (+ admission prefill of the
    warmup) — and after mark_steady a second workload compiles NOTHING,
    at sync and dispatch-ahead depths."""
    from paddle_tpu.observability.program_inventory import (
        get_program_inventory,
    )

    for depth in (0, 2):
        sched = _sched(depth=depth, chunk=8, k=3)
        sched.generate(_prompts(4, seed=7), max_new_tokens=6)
        stats = sched.compile_stats()
        assert stats["compiles"] == sched.num_programs()
        # ProgramInventory pins the enlarged program set: the [S,1]
        # decode grid plus the chunk and verify programs are all live
        inv = get_program_inventory()
        S = sched.config.max_num_seqs
        assert any(f"i32[{S},1]" in e.signature
                   for e in inv.entries(
                       name_contains=sched._step_fn.tracker_name))
        assert list(inv.entries(
            name_contains=sched._chunk_step.tracker_name))
        assert any(f"i32[{S},4]" in e.signature     # [S, 1+k], k=3
                   for e in inv.entries(
                       name_contains=sched._spec_step.tracker_name))
        sched.mark_steady()
        sched.generate(_prompts(5, seed=8), max_new_tokens=6)
        stats = sched.compile_stats()
        assert stats["steady_state_recompiles"] == 0, stats
        sched.shutdown()
        _pool_clean(sched)


# ------------------------------------------------------- observability

def test_tracer_chunk_events_and_flight_chunked_tokens():
    """Satellite contract: per-chunk ``prefill_chunk`` events (offset +
    size) on the request timeline, and the flight recorder's per-step
    ``chunked_tokens`` field."""
    rng = np.random.default_rng(9)
    long_prompt = rng.integers(0, 1000, 40)
    sched = _sched(chunk=8)
    rid = sched.add_request(long_prompt, max_new_tokens=3)
    guard = 2000
    while sched.has_unfinished():
        sched.step()
        guard -= 1
        assert guard > 0
    C = sched._chunk_size              # chunk=8 buckets up to 16
    tr = sched.tracer.get(rid).to_dict()
    chunks = [e for e in tr["events"] if e["name"] == "prefill_chunk"]
    assert len(chunks) == -(-40 // C)
    offs = [c["offset"] for c in chunks]
    assert offs == sorted(offs) and offs[0] == 0
    assert sum(c["size"] for c in chunks) == 40
    assert all(0 < c["size"] <= C for c in chunks)
    steps = sched.flight.dump()
    assert all("chunked_tokens" in r for r in steps)
    assert sum(r["chunked_tokens"] for r in steps) == 40
    sched.shutdown()
    _pool_clean(sched)


def test_spec_stats_and_stall_phase():
    """spec_stats reports the accept accounting; the host-side proposal
    walk is attributed to the new ``spec_propose`` stall phase."""
    from paddle_tpu.observability.serving_stall import STALL_PHASES

    assert "spec_propose" in STALL_PHASES
    sched = _sched(k=3)
    assert sched.spec_stats() is None or sched.spec_stats()["verify_steps"] == 0
    sched.generate(_prompts(4, seed=11), max_new_tokens=8)
    st = sched.spec_stats()
    assert st["verify_steps"] > 0
    assert st["proposed_tokens"] >= st["accepted_tokens"] >= 0
    assert 0.0 <= st["accept_rate"] <= 1.0
    assert st["tokens_per_verify_step"] >= 1.0
    assert st["emitted_tokens"] >= st["verify_steps"]
    assert sched.stall.snapshot()["spec_propose"] > 0
    sched.shutdown()
    _pool_clean(sched)
    # chunk/spec off: the feature surface reports absent, not zero
    plain = _sched()
    assert plain.spec_stats() is None
    assert "chunked_tokens" not in (plain.flight.dump() or [{}])[0]
    plain.shutdown()
