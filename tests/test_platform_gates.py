"""Platform-gate portability (VERDICT r4 weak #6): the is_tpu_like gates
are exercised on BOTH branches by mocking a second accelerator platform —
the kernels' route decisions must flip with the platform, and the XLA
fallback must produce identical numerics to the (interpreted) Pallas path
so a future second backend starts from a correct baseline."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.device as device_mod


class _FakeDev:
    def __init__(self, platform):
        self.platform = platform


@pytest.fixture
def fake_platform(monkeypatch):
    def set_platform(name):
        monkeypatch.setattr(jax, "devices",
                            lambda *a, **k: [_FakeDev(name)])
    return set_platform


def test_is_tpu_like_flips_with_platform(fake_platform):
    fake_platform("tpu")
    assert device_mod.is_tpu_like()
    fake_platform("axon")
    assert device_mod.is_tpu_like()
    fake_platform("cpu")
    assert not device_mod.is_tpu_like()
    fake_platform("oneapi")  # a hypothetical second vendor accelerator
    assert not device_mod.is_tpu_like()
    assert device_mod.is_tpu_like_platform("tpu")
    assert not device_mod.is_tpu_like_platform("oneapi")


def test_flash_gate_selects_xla_on_foreign_platform(fake_platform,
                                                    monkeypatch):
    from paddle_tpu.ops.pallas import flash_attention as fa

    # the gate function consults is_tpu_like -> devices()
    fake_platform("oneapi")
    monkeypatch.setattr(fa, "_last_path", None)
    q = jnp.ones((1, 128, 2, 64), jnp.float32) * 0.1

    from paddle_tpu.tensor import Tensor

    out = fa.flash_attention(
        Tensor._from_value(q), Tensor._from_value(q),
        Tensor._from_value(q))
    val = out[0] if isinstance(out, tuple) else out
    assert np.isfinite(np.asarray(val.numpy())).all()
    assert fa._last_path == "xla"  # foreign platform must not take pallas


def test_fused_rms_gate_flips(fake_platform):
    from paddle_tpu.ops.pallas import fused_rms_norm as frn

    fake_platform("tpu")
    assert frn.use_fused_rms_norm(1024)       # eligible shape on tpu
    assert not frn.use_fused_rms_norm(100)    # ineligible shape anywhere
    fake_platform("oneapi")
    assert not frn.use_fused_rms_norm(1024)   # foreign platform: XLA


def test_fused_adamw_gate_flips(fake_platform):
    from paddle_tpu.ops.pallas import fused_adamw as fad

    fake_platform("axon")
    assert fad.use_fused_adamw()
    fake_platform("rocm")
    assert not fad.use_fused_adamw()


def test_rms_norm_fallback_matches_interpreted_kernel():
    """Numerical contract across the gate: the XLA composition and the
    Pallas kernel (interpret mode — runs on any backend) agree, so
    flipping the gate for a new platform cannot change results."""
    from paddle_tpu.ops.pallas import fused_rms_norm as frn

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 256)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    ref = frn.rms_ref(x, w, 1e-6)
    pal = frn.rms_norm_pallas(x, w, 1e-6, interpret=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
