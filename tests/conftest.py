"""Test harness config: force an 8-device virtual CPU platform BEFORE jax
initializes, so multi-chip sharding paths (Mesh/pjit/shard_map) are exercised
without TPU hardware — the reference's pattern of testing a hardware backend
on a fake device (test/custom_runtime/test_collective_process_group_xccl.py).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Semantics tests want exact math; the session default emulates TPU bf16 matmul.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A plugin may import jax before this conftest; set config directly too
# (effective as long as the backend isn't initialized yet).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
