"""Test harness config: force an 8-device virtual CPU platform BEFORE jax
initializes, so multi-chip sharding paths (Mesh/pjit/shard_map) are exercised
without TPU hardware — the reference's pattern of testing a hardware backend
on a fake device (test/custom_runtime/test_collective_process_group_xccl.py).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# The axon tunnel plugin's sitecustomize binds jax to the tunnel in any
# FRESH interpreter whose env carries PALLAS_AXON_POOL_IPS — even with
# JAX_PLATFORMS=cpu (NOTES_r4 container gotcha). The CPU tier (and every
# subprocess it spawns: launcher drills, multihost workers, trial runners)
# must not depend on tunnel liveness, so drop it from the inherited env.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Semantics tests want exact math; the session default emulates TPU bf16 matmul.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Persistent compile cache: OFF for tests by default, PADDLE_TPU_TEST_CACHE=1
# opts in. The stamped dir (framework+jax versions, auto-wiped on mismatch)
# was built after NOTES r7's stale-cache corruption, but stamping cannot
# catch the residual hole: the SAME build's cache occasionally replays an
# XLA:CPU AOT executable with wrong numerics (decode programs with donated
# buffers; the per-module _no_aot_replay fences protect the serving modules'
# own compiles, not executables replayed earlier in the process). Measured on
# the tier-1 box: ~3 corrupt runs in 22 with the cache vs 0 in 8 without,
# while a cold-cache full suite costs only ~3% more wall than a warm one —
# determinism of the primary gate wins. Benches keep the cache (bench.py
# wires it independently). Loaded by file path: importing paddle_tpu here
# would initialize jax before the env pinning above.
if os.environ.get("PADDLE_TPU_TEST_CACHE") == "1":
    import importlib.util as _ilu

    _repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _spec = _ilu.spec_from_file_location(
        "_pt_compile_cache",
        os.path.join(_repo_root, "paddle_tpu", "utils", "compile_cache.py"))
    _cc = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_cc)
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        _cc.ensure_compile_cache_dir(
            os.path.join(_repo_root, "build", "jax_cache")))

import jax  # noqa: E402

# The env vars above are NOT enough when something imported jax before this
# conftest — in particular the axon sitecustomize, whose register() sets the
# effective jax_platforms to "axon,cpu" in-config, so first backend use
# would still dial the tunnel (and hang forever when it's dead — liveness
# flaps). Backends are not yet initialized at conftest time, so an explicit
# config update pins CPU.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Files dominated by big compiles / model fixtures / process spawns get the
# `slow` marker automatically, giving a quick tier (`pytest -m "not slow"`,
# ~2-3 min) for iteration — VERDICT r1 weak #10 (13-min full suite).
_SLOW_FILES = {
    "test_io_amp_jit.py",
    "test_serving.py",
    "test_generation.py",
    "test_moe_llama_ckpt.py",
    "test_sharding_stages.py",
    "test_vision_hapi.py",
    "test_bert_vit_audio.py",
    "test_multiprocess_dist.py",
    "test_tuner_text.py",
    "test_pipeline_schedules.py",
    "test_distributed.py",
    "test_inference_varlen_ernie.py",
    "test_fused_lamb.py",
    # r5 tiering (VERDICT r4 weak #5): the compile-heavy model/hybrid
    # drills measured >30 s each move to the slow tier
    "test_vision_models_r4.py",
    "test_engine_hybrid_3axis.py",
    "test_ring_profiler.py",
    "test_auto_parallel_engine.py",
    "test_rnn_layers.py",
    "test_quantization_pipeline.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.path is not None and item.path.name in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
