"""Eager collective semantics on the 8-device mesh (reference oracles:
test/collective/collective_allreduce_api.py family). The stacked [world, ...]
encoding plays all ranks in one controller."""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import collective as C

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices"
)

pytestmark = requires_8


def _stack(vals):
    return C.shard_from_host(np.asarray(vals, dtype=np.float32))


def setup_module():
    dist.init_parallel_env()


def test_all_reduce_world():
    t = _stack([float(r) for r in range(8)])
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), [28.0] * 8)


def test_all_reduce_max():
    t = _stack([float(r) for r in range(8)])
    dist.all_reduce(t, op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(t.numpy(), [7.0] * 8)


def test_all_reduce_contiguous_subgroups():
    g = C.new_group([0, 1, 2, 3])  # implies blocks {0-3},{4-7}
    t = _stack([float(r) for r in range(8)])
    dist.all_reduce(t, group=g)
    np.testing.assert_allclose(t.numpy(), [6.0] * 4 + [22.0] * 4)


def test_all_reduce_strided_subgroups():
    # dp-style strided partition {0,2,4,6}, {1,3,5,7}
    g = C.new_group([0, 2, 4, 6], partition=[[0, 2, 4, 6], [1, 3, 5, 7]])
    t = _stack([float(r) for r in range(8)])
    dist.all_reduce(t, group=g)
    expect = [12.0, 16.0] * 4
    np.testing.assert_allclose(t.numpy(), expect)


def test_broadcast():
    t = _stack([float(r) for r in range(8)])
    dist.broadcast(t, src=3)
    np.testing.assert_allclose(t.numpy(), [3.0] * 8)


def test_broadcast_subgroups_local_src():
    g = C.new_group([0, 1, 2, 3])
    t = _stack([float(r) for r in range(8)])
    dist.broadcast(t, src=1, group=g)  # local position 1 in each block
    np.testing.assert_allclose(t.numpy(), [1.0] * 4 + [5.0] * 4)


def test_reduce_only_dst_updated():
    g = C.new_group([4, 5, 6, 7])
    t = _stack([float(r) for r in range(8)])
    dist.reduce(t, dst=5, group=g)
    expect = [0, 1, 2, 3, 4, 22, 6, 7]
    np.testing.assert_allclose(t.numpy(), expect)


def test_all_gather_world():
    t = _stack([float(r) * 10 for r in range(8)])
    outs = []
    dist.all_gather(outs, t)
    assert len(outs) == 8
    for j, o in enumerate(outs):
        np.testing.assert_allclose(o.numpy(), j * 10.0)


def test_all_gather_subgroups_stacked():
    g = C.new_group([0, 1, 2, 3])
    t = _stack([float(r) for r in range(8)])
    outs = []
    dist.all_gather(outs, t, group=g)
    assert len(outs) == 4
    # entry j, rank r slice = value of j-th member of r's block
    np.testing.assert_allclose(outs[1].numpy(), [1.0] * 4 + [5.0] * 4)


def test_reduce_scatter():
    # each rank holds [8] vector of its rank value; group = world (8 ranks),
    # chunks of size 1 per rank
    vals = np.tile(np.arange(8.0, dtype=np.float32)[:, None], (1, 8)).reshape(8, 8, 1)
    t = C.shard_from_host(vals)  # [world, gsize, 1]
    out = paddle.zeros([8, 1])
    dist.reduce_scatter(out, t)
    np.testing.assert_allclose(out.numpy(), np.full((8, 1), 28.0))


def test_all_to_all():
    # rank r's in[j] = r*10 + j; after a2a, rank r's out[j] = j*10 + r
    ins = []
    for j in range(8):
        ins.append(_stack([float(r * 10 + j) for r in range(8)]))
    outs = []
    dist.all_to_all(outs, ins)
    for j in range(8):
        np.testing.assert_allclose(
            outs[j].numpy(), [float(j * 10 + r) for r in range(8)]
        )


def test_scatter_from_src():
    # tensor_list[j] as held by rank s = s*100 + j; src=0 -> rank r gets 0*100+r
    tl = [_stack([float(s * 100 + j) for s in range(8)]) for j in range(8)]
    t = paddle.zeros([8])
    dist.scatter(t, tl, src=0)
    np.testing.assert_allclose(t.numpy(), [float(r) for r in range(8)])


def test_send_recv_matching():
    a = paddle.to_tensor([1.0])
    b = paddle.to_tensor([2.0])
    dist.send(a, dst=1)
    dist.send(b, dst=2)
    out = paddle.zeros([1])
    dist.recv(out, src=0)
    np.testing.assert_allclose(out.numpy(), [1.0])
    dist.recv(out, src=0)
    np.testing.assert_allclose(out.numpy(), [2.0])


def test_hybrid_topology_groups_strided():
    from paddle_tpu.distributed.fleet.topology import HybridCommunicateGroup

    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=4)
    dp = hcg.get_data_parallel_group()
    # topo order (data, pipe, sharding, sep, model): dp peers stride by mp
    assert dp.partition == [[0, 4], [1, 5], [2, 6], [3, 7]], dp.partition
    mp = hcg.get_model_parallel_group()
    assert mp.partition == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # gradient-style allreduce over the dp axis
    t = _stack([float(r) for r in range(8)])
    dist.all_reduce(t, group=dp)
    np.testing.assert_allclose(t.numpy(), [4.0, 6.0, 8.0, 10.0] * 2)


def test_mesh_matches_topology_ranks():
    from paddle_tpu.distributed.fleet.topology import HybridCommunicateGroup

    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    mesh = hcg.get_mesh()
    assert mesh.devices.shape == (2, 2, 1, 1, 2)
    # device at mesh coord == topology rank
    topo = hcg.topology()
    flat = mesh.devices.flatten()
    for rank in range(8):
        assert flat[rank].id == jax.devices()[rank].id
        assert topo.get_coord(rank) == tuple(
            np.unravel_index(rank, (2, 2, 1, 1, 2))
        )
