"""Round-2 SPMD oracle expansion (VERDICT r1 row 7: 5 rule families tested
vs ~30 reference rule files). Each test pins GSPMD's propagation against the
corresponding explicit rule in paddle/phi/infermeta/spmd_rules/*.cc —
softmax, transpose, concat, split, slice, reshape/flatten/squeeze, cumsum,
triu, tile, stack, unbind, gather, scatter, one_hot, cast/scale/pow (unary
family), cross_entropy_with_softmax, expand_as, full_like, swiglu, fused
rope, argmax, numel — covering the remaining rule surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices")


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "mp"))


def _put(arr, mesh, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _spec_of(arr):
    return tuple(arr.sharding.spec)


# --------------------------------------------------------- elementwise-like


@requires_8
def test_softmax_keeps_batch_shard_when_reducing_last():
    # softmax.cc: softmax over the last dim keeps leading shards
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: jax.nn.softmax(a, axis=-1))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_cast_scale_pow_preserve_sharding():
    # cast.cc / scale.cc / pow.cc: unary elementwise keeps the input dist
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", "mp"))
    for fn in (lambda a: a.astype(jnp.bfloat16),
               lambda a: a * 3.0,
               lambda a: a ** 2):
        out = jax.jit(fn)(x)
        assert _spec_of(out) == ("dp", "mp"), fn


@requires_8
def test_cumsum_along_unsharded_axis_keeps_shard():
    # cumsum.cc: scan along an unsharded dim preserves other dims' shards
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: jnp.cumsum(a, axis=1))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_triu_keeps_leading_shard():
    # triu.cc: masking is elementwise over the matrix dims
    mesh = _mesh()
    x = _put(np.random.rand(8, 16, 16).astype(np.float32), mesh,
             P("dp", None, None))
    out = jax.jit(jnp.triu)(x)
    assert _spec_of(out)[0] == "dp"


# ------------------------------------------------------------ dim transforms


@requires_8
def test_transpose_permutes_shard_axes():
    # transpose.cc: out dims_mapping is the permuted input mapping
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", "mp"))
    out = jax.jit(lambda a: a.T)(x)
    assert _spec_of(out) == ("mp", "dp")


@requires_8
def test_reshape_merge_keeps_outer_shard():
    # reshape.cc: merging [B(dp), S, H] -> [B*S, H] keeps dp on the merged dim
    mesh = _mesh()
    x = _put(np.random.rand(8, 4, 16).astype(np.float32), mesh,
             P("dp", None, None))
    out = jax.jit(lambda a: a.reshape(32, 16))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_flatten_squeeze_keep_shard():
    # flatten.cc / squeeze.cc
    mesh = _mesh()
    x = _put(np.random.rand(8, 1, 16).astype(np.float32), mesh,
             P("dp", None, None))
    out = jax.jit(lambda a: jnp.squeeze(a, 1))(x)
    assert _spec_of(out)[0] == "dp"  # (trailing replicated dims trimmed)


@requires_8
def test_tile_keeps_untiled_shard():
    # tile.cc: a dim tiled by 1 keeps its shard
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: jnp.tile(a, (1, 2)))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_expand_as_broadcast_dim_replicated():
    # expand_as.cc: broadcast dims come out replicated, kept dims keep shard
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: jnp.broadcast_to(a[:, None, :], (8, 4, 16)))(x)
    assert _spec_of(out)[0] == "dp"


# ------------------------------------------------------------- concat/split


@requires_8
def test_concat_along_unsharded_axis_keeps_shard():
    # concat.cc: concat on a non-sharded dim preserves the other shards
    mesh = _mesh()
    a = _put(np.random.rand(8, 8).astype(np.float32), mesh, P("dp", None))
    b = _put(np.random.rand(8, 8).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda x, y: jnp.concatenate([x, y], axis=1))(a, b)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_split_keeps_other_dims_shard():
    # split.cc
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    outs = jax.jit(lambda a: jnp.split(a, 2, axis=1))(x)
    for o in outs:
        assert _spec_of(o)[0] == "dp"


@requires_8
def test_stack_unbind_shard_flow():
    # stack.cc / unbind.cc: new axis is replicated; removing it restores
    mesh = _mesh()
    a = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    st = jax.jit(lambda x: jnp.stack([x, x], axis=0))(a)
    assert _spec_of(st)[1] == "dp"
    un = jax.jit(lambda s: s[0])(st)
    assert _spec_of(un)[0] == "dp"


@requires_8
def test_slice_keeps_unsliced_shard():
    # slice.cc: slicing dim 1 keeps dp on dim 0
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: a[:, 2:10])(x)
    assert _spec_of(out)[0] == "dp"


# ----------------------------------------------------------- gather/scatter


@requires_8
def test_gather_batch_shard_preserved():
    # gather.cc: indexing dim 1 with replicated indices keeps dp on dim 0
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    idx = jnp.asarray([0, 3, 5])
    out = jax.jit(lambda a, i: jnp.take(a, i, axis=1))(x, idx)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_scatter_add_keeps_dest_shard():
    # scatter.cc: scatter into an unsharded dim keeps the batch shard
    mesh = _mesh()
    x = _put(np.zeros((8, 16), np.float32), mesh, P("dp", None))
    idx = jnp.asarray([1, 4])
    upd = jnp.ones((8, 2), jnp.float32)
    out = jax.jit(lambda a, i, u: a.at[:, i].add(u))(x, idx, upd)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_one_hot_new_class_dim_replicated():
    # one_hot.cc: the new class dim is replicated, input shard kept
    mesh = _mesh()
    ids = _put(np.zeros((8,), np.int32), mesh, P("dp"))
    out = jax.jit(lambda i: jax.nn.one_hot(i, 16))(ids)
    assert _spec_of(out)[0] == "dp"


# ------------------------------------------------- losses / fused / queries


@requires_8
def test_cross_entropy_with_softmax_batch_shard():
    # cross_entropy_with_softmax.cc: batch shard survives through CE
    mesh = _mesh()
    logits = _put(np.random.rand(8, 32).astype(np.float32), mesh,
                  P("dp", None))
    labels = _put(np.zeros((8,), np.int32), mesh, P("dp"))

    def ce(lg, lb):
        lse = jax.nn.logsumexp(lg, axis=-1)
        picked = jnp.take_along_axis(lg, lb[:, None], 1)[:, 0]
        return lse - picked

    out = jax.jit(ce)(logits, labels)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_swiglu_keeps_shards():
    # swiglu.cc: elementwise over two halves keeps both mappings
    mesh = _mesh()
    a = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", "mp"))
    b = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", "mp"))
    out = jax.jit(lambda x, y: jax.nn.silu(x) * y)(a, b)
    assert _spec_of(out) == ("dp", "mp")


@requires_8
def test_rope_keeps_seq_and_head_shards():
    # fused_rope.cc: rotation is elementwise in the head dim
    mesh = _mesh()
    q = _put(np.random.rand(2, 8, 4, 16).astype(np.float32), mesh,
             P(None, "dp", "mp", None))

    def rope(x):
        half = x.shape[-1] // 2
        cos = jnp.ones((x.shape[1], half), x.dtype)[None, :, None, :]
        sin = jnp.zeros((x.shape[1], half), x.dtype)[None, :, None, :]
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x1 * sin + x2 * cos], -1)

    out = jax.jit(rope)(q)
    assert _spec_of(out)[1] == "dp"
    assert _spec_of(out)[2] == "mp"


@requires_8
def test_argmax_removes_reduced_dim_shard():
    # argmax.cc: reducing the sharded dim forces a gather; reducing an
    # unsharded dim keeps the rest
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: jnp.argmax(a, axis=1))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_full_like_follows_reference_operand():
    # full_like.cc: the filled tensor adopts the operand's dist attr when
    # the consumer needs it (GSPMD: constant is free to take any sharding —
    # assert the ADD forces consistency, the rule's real contract)
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", None))
    out = jax.jit(lambda a: a + jnp.full_like(a, 2.0))(x)
    assert _spec_of(out)[0] == "dp"


@requires_8
def test_numel_is_replicated_scalar():
    # numel.cc: the count is a replicated scalar regardless of input shard
    mesh = _mesh()
    x = _put(np.random.rand(8, 16).astype(np.float32), mesh, P("dp", "mp"))
    out = jax.jit(lambda a: jnp.asarray(a.size))(x)
    assert out.sharding.is_fully_replicated
