"""Autograd engine tests (reference: eager backward semantics,
paddle/fluid/eager/backward.cc; numeric oracles are closed forms)."""

import numpy as np

import paddle_tpu as paddle


def test_scalar_backward():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 6.0)


def test_chain_rule():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.sum(paddle.exp(x) * x)
    y.backward()
    expect = np.exp([1.0, 2.0]) * (1 + np.array([1.0, 2.0]))
    np.testing.assert_allclose(x.grad.numpy(), expect, rtol=1e-5)


def test_grad_accumulation_two_uses():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x + 3.0 * x  # dy/dx = 2x + 3 = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 7.0)


def test_backward_twice_accumulates():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 2).backward()
    np.testing.assert_allclose(x.grad.numpy(), 4.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    y = paddle.to_tensor(1.0, stop_gradient=True)
    z = x * y
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = (x * x).detach()
    z = y * 3.0
    z.backward()
    assert x.grad is None


def test_no_grad_context():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    with paddle.no_grad():
        y = x * x
    assert y._node is None


def test_matmul_grad():
    a = np.random.default_rng(0).normal(size=(2, 3)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32)
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    loss = paddle.sum(paddle.matmul(ta, tb))
    loss.backward()
    gones = np.ones((2, 4), dtype=np.float32)
    np.testing.assert_allclose(ta.grad.numpy(), gones @ b.T, rtol=1e-5)
    np.testing.assert_allclose(tb.grad.numpy(), a.T @ gones, rtol=1e-5)


def test_paddle_grad_api():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, [x])
    np.testing.assert_allclose(g.numpy(), 27.0, rtol=1e-6)
    # .grad untouched
    assert x.grad is None


def test_non_scalar_backward_requires_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_broadcast_grad_reduces():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    b = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    y = paddle.sum(x + b)
    y.backward()
    np.testing.assert_allclose(b.grad.numpy(), [2.0, 2.0])


def test_retain_grads_intermediate():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.retain_grads()
    z = y * 3.0
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), 3.0)
