"""Sharded (scale-out) parameter server (r5; reference ps_client.h:64
routes per-key to shard owners, dense params partition across servers).
Drills: routing exactness vs per-shard accessor math, dense partitioning,
async push + barrier, save/load shard files, a sharded embedding training
loop, and a 2-rpc-server process drill."""

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (
    _NAMESPACES,
    PSClient,
    ShardedPSClient,
)


@pytest.fixture
def sharded():
    c = ShardedPSClient([PSClient(namespace=f"shard{i}") for i in range(3)])
    yield c
    for i in range(3):
        _NAMESPACES.get(f"shard{i}", {}).clear()


def test_sparse_routing_exactness(sharded):
    """pull after push must reflect each key's OWN shard state — verify
    against locally computed SGD accessor math per key."""
    dim, lr = 4, 0.1
    sharded.create_sparse_table(0, dim=dim, accessor="sgd", lr=lr,
                                init_range=0.0)  # rows init to zeros
    ids = [0, 1, 2, 3, 4, 5, 7, 300, 301]
    first = sharded.pull_sparse(0, ids)
    np.testing.assert_allclose(first, 0.0)
    grads = np.arange(len(ids) * dim, dtype=np.float32).reshape(-1, dim)
    sharded.push_sparse(0, ids, grads)
    after = sharded.pull_sparse(0, ids)
    np.testing.assert_allclose(after, -lr * grads, rtol=1e-6)
    # duplicate ids in one pull: both positions get the same row
    dup = sharded.pull_sparse(0, [7, 7, 300])
    np.testing.assert_allclose(dup[0], dup[1])
    # total rows spread over shards
    assert sharded.table_size(0) == len(ids)
    # every shard holds only its residue class
    for i in range(3):
        for tid, table in _NAMESPACES[f"shard{i}"].items():
            assert all(k % 3 == i for k in table._rows), (i, table._rows)


def test_dense_partition_roundtrip(sharded):
    dim, lr = 10, 0.5  # 10 = 4+3+3 over 3 shards
    sharded.create_dense_table(1, dim=dim, lr=lr)
    v0 = sharded.pull_dense(1)
    assert v0.shape == (dim,)
    g = np.arange(dim, dtype=np.float32)
    sharded.push_dense(1, g)
    v1 = sharded.pull_dense(1)
    np.testing.assert_allclose(v1, v0 - lr * g, rtol=1e-6)


def test_async_push_and_barrier(sharded):
    dim = 4
    sharded.create_sparse_table(2, dim=dim, accessor="sgd", lr=1.0,
                                init_range=0.0)
    ids = list(range(9))
    g = np.ones((9, dim), np.float32)
    sharded.push_sparse(2, ids, g, async_push=True)
    sharded.barrier()
    np.testing.assert_allclose(sharded.pull_sparse(2, ids), -1.0)


def test_save_load_shard_files(sharded, tmp_path):
    dim = 4
    sharded.create_sparse_table(3, dim=dim, accessor="sgd", lr=0.1)
    ids = [1, 2, 3, 4, 5]
    _ = sharded.pull_sparse(3, ids)
    before = sharded.pull_sparse(3, ids)
    path = str(tmp_path / "table3")
    sharded.save(3, path)
    import os

    assert all(os.path.exists(f"{path}.shard{i}") for i in range(3))
    # wipe and reload
    for i in range(3):
        _NAMESPACES[f"shard{i}"][3]._rows.clear()
    sharded.load(3, path)
    np.testing.assert_allclose(sharded.pull_sparse(3, ids), before)


def test_sharded_embedding_model_trains(sharded):
    dim = 8
    sharded.create_sparse_table(5, dim=dim, accessor="adagrad", lr=0.5)
    rng = np.random.default_rng(0)
    n_feat = 50
    samples = [(rng.integers(0, n_feat, 5), None) for _ in range(64)]
    samples = [(ids, float(np.sum(ids % 2) > 2.5)) for ids, _ in samples]
    losses = []
    for _ in range(30):
        total = 0.0
        for ids, y in samples:
            emb = sharded.pull_sparse(5, ids)
            z = float(emb.sum())
            p = 1.0 / (1.0 + np.exp(-z))
            total += -(y * np.log(p + 1e-9)
                       + (1 - y) * np.log(1 - p + 1e-9))
            grads = np.full((len(ids), dim), (p - y) / dim, np.float32)
            sharded.push_sparse(5, ids, grads, async_push=True)
        sharded.barrier()
        losses.append(total / len(samples))
    assert losses[-1] < 0.5 * losses[0]


@pytest.mark.slow
def test_two_rpc_server_processes():
    """Real scale-out drill: two PS server OS processes behind the
    TCPStore rpc, one sharded client routing between them."""
    import os
    import socket
    import subprocess
    import sys
    import time

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.store import TCPStore

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = r"""
import sys
import paddle_tpu.distributed.rpc as rpc
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.ps import PSServer
rank = int(sys.argv[1])
store = TCPStore("127.0.0.1", %d, is_master=False)
rpc.init_rpc(f"ps{rank}", rank=rank, world_size=3, store=store)
PSServer()  # tables created remotely via create ops
import time
while True:  # the poller thread serves; parent terminates us
    time.sleep(0.5)
""" % port
    store = TCPStore("127.0.0.1", port, is_master=True)
    procs = [subprocess.Popen([sys.executable, "-c", worker, str(r)],
                              cwd=repo_root)
             for r in (1, 2)]
    try:
        rpc.init_rpc("trainer", rank=0, world_size=3, store=store)
        deadline = time.time() + 30
        while time.time() < deadline:
            names = {w.name for w in rpc.get_all_worker_infos()}
            if {"ps1", "ps2"} <= names:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("ps servers never registered")
        c = ShardedPSClient([PSClient("ps1"), PSClient("ps2")])
        c.create_sparse_table(0, dim=4, accessor="sgd", lr=0.5,
                              init_range=0.0)
        ids = [0, 1, 2, 3, 10, 11]
        g = np.ones((6, 4), np.float32)
        c.push_sparse(0, ids, g)
        out = c.pull_sparse(0, ids)
        np.testing.assert_allclose(out, -0.5, rtol=1e-6)
        c.create_dense_table(1, dim=6, lr=1.0)
        c.push_dense(1, np.arange(6, dtype=np.float32))
        v = c.pull_dense(1)
        assert v.shape == (6,)
        assert c.table_size(0) == 6
        rpc.shutdown()
    finally:
        for p in procs:
            p.terminate()
            p.wait(timeout=10)
        time.sleep(0.2)
