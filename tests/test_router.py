"""Fault-tolerant multi-replica serving: router, supervisor, failover.

The failover identity oracle: greedy per-request token streams are
independent of batching, placement, and timing, so a request replayed
from its committed view on a survivor must produce a stream bit-identical
to a single-replica run — the same standard PR 8/10 pinned for retry and
async dispatch. Pinned here across a replica kill mid-decode, plus: zero
block leaks after supervisor reap, the circuit-breaker open→half_open→
closed lifecycle, deadlines measured from FIRST admission across
failover, affinity-vs-health routing precedence, zero-downtime rolling
weight reload, the three router fault sites, and serve_bench's
quiesce-every-replica partial artifact.
"""

import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.resilience import (FaultPlan, InjectedFault, fault_plan,
                                   get_injector)
from paddle_tpu.serving import (
    CircuitBreaker,
    ContinuousBatchingScheduler,
    SchedulerConfig,
    SchedulerOverloaded,
    ServingRouter,
)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts these decode programs' NUMERICS (see
    test_serving_sched.py for the history) — serving tests compile fresh."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=1))


def _factory(model, **over):
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=8)
    kw.update(over)

    def factory():
        return ContinuousBatchingScheduler(model, SchedulerConfig(**kw))

    return factory


def _router(model, n=3, **over):
    sched_over = over.pop("sched", {})
    kw = dict(cooldown_s=0.05, affinity_tokens=8)
    kw.update(over)
    return ServingRouter(_factory(model, **sched_over), num_replicas=n,
                         **kw)


def _prompts(n, seed=0, lo=4, hi=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 1000, int(k))
            for k in rng.integers(lo, hi, n)]


def _oracle(model, prompts, max_new, **over):
    """Single-replica reference streams, rid-indexed in submit order."""
    sched = _factory(model, **over)()
    rids = [sched.add_request(p, max_new_tokens=max_new) for p in prompts]
    guard = 3000
    while sched.has_unfinished():
        sched.step()
        guard -= 1
        assert guard > 0
    outs = dict(sched._finished)
    sched.shutdown()
    return [outs[r].token_ids for r in rids]


def _pools_clean(router):
    for rep in router.replicas:
        sched = rep.sched
        if sched.prefix_cache is not None:
            sched.prefix_cache.flush()
        assert sched.allocator.num_used_blocks == 0, (
            f"replica {rep.replica_id} leaked "
            f"{sched.allocator.num_used_blocks} blocks")


# ------------------------------------------------------- the chaos drill

def test_replica_kill_mid_decode_token_identical_no_leaks(model):
    """The drill: kill a replica mid-decode; every in-flight request
    completes on survivors bit-identical to the single-replica oracle,
    the dead replica's pool drains to zero after reap, and its breaker
    opens then re-closes after cooldown."""
    prompts = _prompts(8, seed=1)
    refs = _oracle(model, prompts, 6)

    router = _router(model, n=3)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):
        router.step()

    dead_sched = router.replicas[0].sched        # the incarnation we kill
    router.crash_replica(0)
    router.step()                                # supervisor reaps here

    # (b) zero leaks on the dead incarnation's pool after reap: export
    # freed every block and flushed its prefix cache
    assert dead_sched.allocator.num_used_blocks == 0
    assert router.replicas[0].sched is not dead_sched   # restarted fresh
    assert router.replicas[0].generation == 1

    # (c) breaker opened on reap...
    br = router.supervisor.breakers[0]
    assert br.state() == "open"
    assert not router.supervisor.routable(router.replicas[0])

    guard = 3000
    while router.has_unfinished():
        router.step()
        guard -= 1
        assert guard > 0, "router did not drain after the kill"
    results = {rid: router.get_finished(rid) for rid in rids}

    # (a) token identity vs the single-replica oracle, every request
    assert sorted(results) == sorted(rids)
    for rid, ref in zip(rids, refs):
        assert results[rid].finish_reason in ("eos", "length")
        np.testing.assert_array_equal(results[rid].token_ids, ref)
    dbg = router.debug_state()
    assert dbg["router"]["failovers"] == 1
    assert dbg["router"]["requests_failed_over"] >= 1
    assert dbg["supervisor"]["restarts"] == 1

    # (c) ...and re-closes after cooldown: a clean probe from half_open
    time.sleep(0.06)
    assert br.state() == "half_open"
    router.supervisor.probe_all()
    assert br.state() == "closed"
    assert router.supervisor.routable(router.replicas[0])

    router.shutdown()
    _pools_clean(router)


def test_failover_streams_each_token_exactly_once(model):
    """The streaming contract survives failover: on_token fires once per
    generated token, never replaying the committed prefix to the client."""
    prompts = _prompts(4, seed=3)
    counts = {}

    router = _router(model, n=2)
    rids = [router.submit(p, max_new_tokens=6,
                          on_token=lambda rid, tok:
                          counts.__setitem__(rid, counts.get(rid, 0) + 1))
            for p in prompts]
    for _ in range(2):
        router.step()
    router.crash_replica(0)
    results = router.run()
    for rid in rids:
        assert counts.get(rid, 0) == len(results[rid].generated_ids)
    router.shutdown()
    _pools_clean(router)


# ------------------------------------- deadlines measured from admission

def test_deadline_breach_spans_replica_kill(model):
    """A re-queued request must NOT get a fresh deadline budget: the
    original arrival timestamp rides through failover, so a budget that
    would survive if re-measured from the re-queue still breaches."""
    prompt = _prompts(1, seed=5, lo=6, hi=7)[0]
    router = _router(model, n=2)
    # budget 0.3s; we burn ~0.2s before the kill and ~0.2s after it: a
    # fresh budget at re-queue would leave 0.1s of slack, the original
    # clock is 0.1s overdrawn
    rid = router.submit(prompt, max_new_tokens=50, deadline_s=0.3)
    router.step()
    time.sleep(0.2)
    router.crash_replica(0)
    router.step()                                # reap + failover
    assert router.debug_state()["router"]["requests_failed_over"] == 1
    time.sleep(0.2)
    results = router.run()
    assert results[rid].finish_reason == "deadline"
    router.shutdown()
    _pools_clean(router)


# ------------------------------------------------ routing + affinity

def test_affinity_pins_prefix_to_one_replica(model):
    """Requests sharing >= affinity_tokens of prompt land on the replica
    whose radix tree holds the prefix."""
    rng = np.random.default_rng(9)
    shared = rng.integers(0, 1000, 8)
    prompts = [np.concatenate([shared, rng.integers(0, 1000, 4)])
               for _ in range(4)]
    router = _router(model, n=3, sched=dict(enable_prefix_caching=True))
    rids = [router.submit(p, max_new_tokens=3) for p in prompts]
    with router._lock:
        homes = {router._records[r].replica_id for r in rids}
    assert len(homes) == 1, f"shared prefix scattered over {homes}"
    router.run()
    # the bound replica's radix tree served the repeats
    home = homes.pop()
    assert router.replicas[home].sched.prefix_cache.stats()["hit_rate"] > 0
    router.shutdown()
    _pools_clean(router)


def test_health_gate_outranks_affinity(model):
    """A draining/reloading replica loses its affinity traffic: health is
    checked before the prefix binding, never after."""
    rng = np.random.default_rng(10)
    shared = rng.integers(0, 1000, 8)

    def prompt():
        return np.concatenate([shared, rng.integers(0, 1000, 4)])

    router = _router(model, n=2)
    r0 = router.submit(prompt(), max_new_tokens=3)
    with router._lock:
        home = router._records[r0].replica_id
    router.replicas[home].begin_reload()         # out of the routing set
    r1 = router.submit(prompt(), max_new_tokens=3)
    with router._lock:
        moved = router._records[r1].replica_id
    assert moved != home
    router.replicas[home].end_reload()
    router.run()
    router.shutdown()
    _pools_clean(router)


def test_no_routable_replica_rejects(model):
    router = _router(model, n=2)
    for rep in router.replicas:
        rep.begin_reload()
    with pytest.raises(SchedulerOverloaded, match="no routable replica"):
        router.submit(_prompts(1)[0], max_new_tokens=3)
    assert router.metrics.requests_rejected == 1
    router.shutdown()


def test_round_robin_spreads_load(model):
    router = _router(model, n=3, policy="round_robin")
    rids = [router.submit(p, max_new_tokens=3)
            for p in _prompts(6, seed=11)]
    with router._lock:
        homes = [router._records[r].replica_id for r in rids]
    assert set(homes) == {0, 1, 2}
    router.run()
    router.shutdown()
    _pools_clean(router)


# ------------------------------------------------ rolling weight reload

def test_rolling_reload_zero_downtime_token_identical(model, tmp_path):
    """Reload every replica behind live traffic: requests in flight during
    the rollout all finish, streams stay bit-identical (same weights), and
    every replica reports the loaded step."""
    from paddle_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(3, model=model)

    prompts = _prompts(6, seed=13)
    refs = _oracle(model, prompts, 5)
    router = _router(model, n=2)
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    router.step()
    loaded = router.rolling_reload(mgr)
    assert loaded == [3, 3]
    results = router.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(results[rid].token_ids, ref)
    assert router.health()["state"] == "ok"
    router.shutdown()
    _pools_clean(router)


# ------------------------------------------------ router fault sites

def test_route_site_transient_is_absorbed(model):
    router = _router(model, n=2)
    with fault_plan(FaultPlan(seed=0).on("router.route", prob=1.0)):
        rid = router.submit(_prompts(1)[0], max_new_tokens=3)
    results = router.run()
    assert results[rid].finish_reason in ("eos", "length")
    assert router.metrics.faults_snapshot() == {
        'outcome="fired",site="router.route"': 1.0}
    router.shutdown()
    _pools_clean(router)


def test_route_site_fatal_propagates(model):
    router = _router(model, n=2)
    with fault_plan(FaultPlan(seed=0).on("router.route", at=1,
                                         kind="fatal")):
        with pytest.raises(InjectedFault):
            router.submit(_prompts(1)[0], max_new_tokens=3)
    assert any("fatal" in k for k in router.metrics.faults_snapshot())
    assert not router.has_unfinished()
    router.shutdown()


def test_replica_step_transient_skips_iteration(model):
    prompts = _prompts(4, seed=15)
    refs = _oracle(model, prompts, 5)
    router = _router(model, n=2)
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    with fault_plan(FaultPlan(seed=2).on("replica.step", prob=0.3)):
        results = router.run()
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(results[rid].token_ids, ref)
    assert sum(r.health()["transient_faults"]
               for r in router.replicas) >= 1
    router.shutdown()
    _pools_clean(router)


def test_replica_step_fatal_kills_and_fails_over(model):
    prompts = _prompts(4, seed=16)
    refs = _oracle(model, prompts, 5)
    router = _router(model, n=2)
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    with fault_plan(FaultPlan(seed=0).on("replica.step", at=2,
                                         kind="fatal")):
        results = router.run()
    assert router.debug_state()["router"]["failovers"] == 1
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(results[rid].token_ids, ref)
    router.shutdown()
    _pools_clean(router)


def test_healthcheck_site_trips_breaker_at_threshold(model):
    router = _router(model, n=2, probe_fail_threshold=2, cooldown_s=30.0)
    br = router.supervisor.breakers[0]
    plan = FaultPlan(seed=0)
    plan.on("replica.healthcheck", prob=1.0)
    with fault_plan(plan):
        rep = router.replicas[0]
        h = router.supervisor.probe(rep)
        assert h["state"] == "unknown"
        assert br.state() == "closed"            # 1 failure < threshold 2
        router.supervisor.probe(rep)
    assert br.state() == "open"
    assert not router.supervisor.routable(rep)
    assert any("replica.healthcheck" in k
               for k in router.metrics.faults_snapshot())
    router.shutdown()


def test_disarmed_inject_untouched_by_new_sites():
    """The new sites ride the same disarmed fast path: one None check,
    no per-site state while nothing is armed."""
    inj = get_injector()
    assert not inj.armed
    from paddle_tpu.resilience import inject

    before = inj.snapshot()["hits"]
    for site in ("router.route", "replica.step", "replica.healthcheck"):
        inject(site)                             # must be a no-op
    assert inj.snapshot()["hits"] == before      # nothing recorded


# ------------------------------------------------ breaker + export units

def test_circuit_breaker_lifecycle_fake_clock():
    now = [0.0]
    cb = CircuitBreaker(cooldown_s=10.0, probe_fail_threshold=3,
                        clock=lambda: now[0])
    assert cb.state() == "closed" and cb.allows()
    cb.record_probe(False); cb.record_probe(False)
    assert cb.state() == "closed"                # below threshold
    cb.record_probe(False)
    assert cb.state() == "open" and not cb.allows()
    now[0] = 5.0
    cb.record_probe(True)                        # cooldown not elapsed
    assert cb.state() == "open"
    now[0] = 10.0
    assert cb.state() == "half_open" and cb.allows()
    cb.record_probe(False)                       # half_open trial failed
    assert cb.state() == "open"
    now[0] = 20.0
    assert cb.state() == "half_open"
    cb.record_probe(True)
    assert cb.state() == "closed"
    assert cb.trips == 2


def test_export_import_resumes_token_identical(model):
    """The scheduler-level failover hooks: export drains the committed
    view and frees every block; import replays as a recompute resume with
    the ORIGINAL arrival clock and an honest preemption count."""
    prompts = _prompts(3, seed=20)
    refs = _oracle(model, prompts, 6)

    src = _factory(model)()
    rids = [src.add_request(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):
        src.step()
    specs = src.export_restartable()
    assert src.is_draining
    assert src.allocator.num_used_blocks == 0
    assert {s["request_id"] for s in specs} == set(rids)
    by_rid = {s["request_id"]: s for s in specs}
    mid = sum(len(by_rid[r]["out_tokens"]) for r in rids)
    assert mid >= 1, "export before any decode committed nothing"

    dst = _factory(model)()
    new_rids = [dst.import_resumed(by_rid[r]) for r in rids]
    guard = 2000
    while dst.has_unfinished():
        dst.step()
        guard -= 1
        assert guard > 0
    outs = dict(dst._finished)
    for old, new, ref in zip(rids, new_rids, refs):
        np.testing.assert_array_equal(outs[new].token_ids, ref)
        assert outs[new].num_preemptions >= 1   # failover IS a resume
    dst.shutdown()
    src.shutdown()


# --------------------------------------- serve_bench router death drain

def test_serve_bench_router_mode_quiesces_replicas_on_death(
        tmp_path, monkeypatch):
    """Router-mode bench dying mid-run must quiesce EVERY replica behind
    every live router before the ``completed: false`` artifact lands."""
    import tools.serve_bench as sb

    paddle.seed(7)
    model = GPTForCausalLM(gpt_tiny(num_layers=1))

    def boom(**kw):
        router = sb._track_router(ServingRouter(
            _factory(model), num_replicas=2, cooldown_s=0.05))
        rng = np.random.default_rng(0)
        for _ in range(3):
            router.submit(rng.integers(0, 1000, 6), max_new_tokens=30)
        for _ in range(2):
            router.step()
        assert router.has_unfinished()
        raise RuntimeError("mid-bench death with replicas live")

    sb._LIVE_SCHEDS.clear()
    sb._LIVE_ROUTERS.clear()
    monkeypatch.setattr(sb, "run_router_suite", boom)
    out = tmp_path / "BENCH_dead_router.json"
    with pytest.raises(RuntimeError, match="mid-bench death"):
        sb.main(["--smoke", "--replicas", "2", "--out", str(out)])
    art = json.loads(out.read_text())
    assert art["completed"] is False
    entries = art["quiesced_routers"]
    assert len(entries) == 1
    q = entries[0]
    assert q["error"] is None
    assert q["replicas"] == 2
    assert q["cancelled"] >= 1
    assert q["blocks_leaked"] == 0


# ------------------------------------------- fleet journey kill drill

@pytest.mark.parametrize("depth", [0, 2])
def test_kill_drill_single_journey_track_token_identical(model, depth):
    """The fleet-observability drill: kill a replica mid-decode with
    journey tracing + the metrics sampler enabled. Every failed-over
    request must render as EXACTLY ONE fleet-trace track carrying an
    explicit ``failover`` phase plus router reap/replay spans, its phase
    durations must still sum to E2E (the gapless invariant survives the
    replica hop), and the tokens must stay bit-identical to the
    single-replica oracle at dispatch_depth 0 and 2."""
    prompts = _prompts(6, seed=11)
    max_new = 6
    refs = _oracle(model, prompts, max_new, dispatch_depth=depth)

    router = _router(model, n=3, sched={"dispatch_depth": depth},
                     timeline_interval_s=0.005)
    rids = [router.submit(p, max_new_tokens=max_new) for p in prompts]
    router.timeline.sample_once()        # deterministic inline samples
    for _ in range(3):
        router.step()
        router.timeline.sample_once()

    router.crash_replica(0)
    router.step()                        # supervisor reaps + fails over
    router.timeline.sample_once()

    guard = 3000
    while router.has_unfinished():
        router.step()
        guard -= 1
        assert guard > 0, "router did not drain after the kill"
    results = {rid: router.get_finished(rid) for rid in rids}

    # token identity with the full observability stack on
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(results[rid].token_ids, ref)

    dbg = router.debug_state()
    assert dbg["router"]["failovers"] == 1
    moved = dbg["router"]["requests_failed_over"]
    assert moved >= 1

    # one journey per request; the moved ones carry the replica hop
    journeys = {j.router_rid: j for j in router.fleet.journeys()}
    assert sorted(journeys) == sorted(rids)
    hopped = [j for j in journeys.values() if j.failovers > 0]
    assert len(hopped) == moved

    trace = router.export_fleet_trace()
    ev = trace["traceEvents"]
    for j in hopped:
        tid = j.router_rid
        # exactly ONE track for the failed-over request
        tracks = [e for e in ev if e.get("ph") == "M"
                  and e.get("name") == "thread_name"
                  and e.get("tid") == tid]
        assert len(tracks) == 1
        names = {e["name"] for e in ev
                 if e.get("ph") == "X" and e.get("tid") == tid}
        # the explicit failover span links the replica segments, and the
        # router-side spans frame it on the same single track
        assert "req.failover" in names
        assert {"router.route", "router.reap", "router.replay"} <= names

        # gapless across the hop: phase durations sum to E2E on the
        # survivor's resumed trace, which holds the WHOLE timeline
        seg = j.segments[-1]
        rep = router.replicas[seg["replica_id"]]
        tr = rep.sched.tracer.get(seg["replica_rid"])
        assert tr is not None and tr.finish_t is not None
        total = sum(tr.phase_durations().values())
        assert total == pytest.approx(tr.e2e_s(), abs=1e-6)
        assert tr.phase_count("failover") == 1

    # the sampler actually ran (inline + background thread) and recorded
    # queryable per-replica history; the breaker-open incident captured
    # one correlated postmortem bundle
    assert router.timeline.samples_taken >= 4
    assert any(m.startswith("replica0.") or m.startswith("router.")
               for m in router.timeline.metric_names())
    assert router.postmortems.captures >= 1
    kinds = [b["kind"] for b in router.postmortems.bundles()]
    assert "breaker_open" in kinds
    bundle = [b for b in router.postmortems.bundles()
              if b["kind"] == "breaker_open"][-1]
    assert "journeys" in bundle and "timeline_window" in bundle
    assert "router" in bundle

    router.shutdown()
    assert not router.timeline.snapshot()["sampler_alive"]
    _pools_clean(router)
