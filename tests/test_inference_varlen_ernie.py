"""paddle.inference Predictor, varlen flash attention, ERNIE family.
Oracles: the saving model's eager forward; per-sequence dense attention."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import ErnieForSequenceClassification, ernie_tiny


def test_inference_predictor_roundtrip(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    x = paddle.to_tensor(np.random.randn(2, 8).astype(np.float32))
    expect = np.asarray(net(x).numpy())

    prefix = str(tmp_path / "model")
    paddle.jit.save(net, prefix, input_spec=[x])

    from paddle_tpu import inference

    cfg = inference.Config(prefix + ".stablehlo")
    cfg.enable_memory_optim()
    cfg.disable_gpu()
    predictor = inference.create_predictor(cfg)

    names = predictor.get_input_names()
    assert len(names) == 1
    # handle protocol
    h = predictor.get_input_handle(names[0])
    h.copy_from_cpu(np.asarray(x.numpy()))
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
    # list protocol
    outs = predictor.run([np.asarray(x.numpy())])
    np.testing.assert_allclose(outs[0], expect, rtol=1e-5, atol=1e-6)


def test_flash_attn_unpadded_matches_per_sequence():
    from paddle_tpu.ops.pallas.flash_attention import (
        _attention_reference,
        flash_attn_unpadded,
    )
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    lens = [5, 3, 7]
    H, D = 2, 8
    total = sum(lens)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    q = rng.standard_normal((total, H, D)).astype(np.float32)
    k = rng.standard_normal((total, H, D)).astype(np.float32)
    v = rng.standard_normal((total, H, D)).astype(np.float32)

    out, _ = flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu), causal=True)
    out_np = np.asarray(out.numpy())

    import math
    for b in range(3):
        lo, hi = cu[b], cu[b + 1]
        ref = _attention_reference(
            jnp.asarray(q[None, lo:hi]), jnp.asarray(k[None, lo:hi]),
            jnp.asarray(v[None, lo:hi]), None, True,
            1.0 / math.sqrt(D))
        np.testing.assert_allclose(out_np[lo:hi], np.asarray(ref)[0],
                                   rtol=1e-4, atol=1e-5)


def test_ernie_forward_and_finetune_step():
    paddle.seed(0)
    model = ErnieForSequenceClassification(ernie_tiny(), num_classes=3)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, 1000, (4, 16)).astype(np.int64))
    mask = paddle.to_tensor(np.ones((4, 16), np.int64))
    task = paddle.to_tensor(np.zeros((4, 16), np.int64))
    logits = model(ids, attention_mask=mask, task_type_ids=task)
    assert logits.shape == [4, 3]

    # task embedding changes the representation
    logits2 = model(ids, attention_mask=mask,
                    task_type_ids=paddle.to_tensor(
                        np.ones((4, 16), np.int64)))
    assert not np.allclose(np.asarray(logits.numpy()),
                           np.asarray(logits2.numpy()))

    # one fine-tune step drops the loss
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    y = paddle.to_tensor(rng.integers(0, 3, (4, 1)))
    losses = []
    for _ in range(5):
        loss = ce(model(ids, attention_mask=mask), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_ernie_masked_lm_shape():
    from paddle_tpu.models import ErnieForMaskedLM

    paddle.seed(1)
    model = ErnieForMaskedLM(ernie_tiny())
    ids = paddle.to_tensor(
        np.random.default_rng(0).integers(0, 1000, (2, 12)).astype(np.int64))
    out = model(ids)
    assert out.shape == [2, 12, 1024]


def test_flash_attn_unpadded_decode_packing():
    """Unequal q/k packing (1 query vs L cached keys per sequence): causal
    alignment to sequence ends means each query sees ALL its keys."""
    from paddle_tpu.ops.pallas.flash_attention import flash_attn_unpadded
    import math

    rng = np.random.default_rng(2)
    H, D = 2, 4
    klens = [5, 3]
    cu_q = np.array([0, 1, 2], np.int32)
    cu_k = np.concatenate([[0], np.cumsum(klens)]).astype(np.int32)
    q = rng.standard_normal((2, H, D)).astype(np.float32)
    k = rng.standard_normal((sum(klens), H, D)).astype(np.float32)
    v = rng.standard_normal((sum(klens), H, D)).astype(np.float32)

    out, _ = flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu_q), paddle.to_tensor(cu_k), causal=True)
    out_np = np.asarray(out.numpy())

    for b in range(2):
        lo, hi = cu_k[b], cu_k[b + 1]
        for h in range(H):
            s = (k[lo:hi, h] @ q[b, h]) / math.sqrt(D)
            p = np.exp(s - s.max()); p /= p.sum()
            np.testing.assert_allclose(out_np[b, h], p @ v[lo:hi, h],
                                       rtol=1e-4, atol=1e-5)


def test_ernie_rejects_overlong_sequence():
    from paddle_tpu.models import ErnieModel

    model = ErnieModel(ernie_tiny())
    ids = paddle.to_tensor(np.zeros((1, 256), np.int64))  # max is 128
    with pytest.raises(ValueError):
        model(ids)


def test_predictor_validates_input_count(tmp_path):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    net.eval()
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    prefix = str(tmp_path / "m")
    paddle.jit.save(net, prefix, input_spec=[x])
    from paddle_tpu import inference

    pred = inference.create_predictor(inference.Config(prefix))
    with pytest.raises(ValueError):
        pred.run([np.zeros((2, 4), np.float32), np.zeros((2, 4), np.float32)])
