"""Continuous-batching serving scheduler (paddle_tpu/serving/).

Correctness oracle: per-request EAGER generate() (models/generation.py's
concat-cache loop, itself verified cached==full-context) — the scheduler's
iteration-level batching over the paged slot grid must be token-identical
under greedy decoding, including under forced preemption (tiny block pool)
and EOS early-exit. Plus: zero steady-state recompiles across admissions,
allocator hardening, admission control, metrics/streaming/profiler spans,
the inference-Config bridge, and the offline serve_bench smoke artifact.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.models.kv_cache import BlockAllocator, KVPoolExhausted
from paddle_tpu.serving import (
    ContinuousBatchingScheduler,
    QueueFull,
    Request,
    RequestQueue,
    SchedulerConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts these decode programs' NUMERICS (wrong
    generated tokens) even when the persistent cache was written by the
    SAME jax build in the same session — the NOTES-r7 'stale cache' flake
    was this, and version-stamping the dir (utils/compile_cache.py) cannot
    catch a same-version unsound replay. Serving tests therefore compile
    fresh; the rest of the suite keeps the persistent-cache speedup."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _eager_oracle(model, prompt, max_new):
    out = model.generate(paddle.to_tensor(prompt[None, :].astype(np.int64)),
                         max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out.numpy())[0]


# ---------------------------------------------------------------- allocator

def test_block_allocator_alloc_free_reuse_cycles():
    a = BlockAllocator(num_blocks=6, block_size=4)
    assert a.num_free_blocks == 6 and a.num_used_blocks == 0
    b1 = a.allocate(9)            # 3 blocks
    assert len(b1) == 3 and a.num_used_blocks == 3
    assert a.utilization() == pytest.approx(0.5)
    # 9 live tokens in 12 slots of capacity -> 25% tail slack
    assert a.fragmentation(live_tokens=9) == pytest.approx(0.25)
    a.extend(b1, cur_tokens=9, add_tokens=4)   # grow to 13 -> 4 blocks
    assert len(b1) == 4
    a.free(b1)
    assert a.num_free_blocks == 6 and a.num_used_blocks == 0
    # freed blocks are reusable
    b2 = a.allocate(24)
    assert sorted(b2) == list(range(6))
    with pytest.raises(KVPoolExhausted):
        a.allocate(1)
    a.free(b2)


def test_block_allocator_double_free_raises():
    a = BlockAllocator(num_blocks=4, block_size=4)
    blocks = a.allocate(8)
    a.free(blocks)
    with pytest.raises(RuntimeError, match="double free"):
        a.free(blocks)
    with pytest.raises(RuntimeError, match="double free"):
        a.free([99])              # never owned


# -------------------------------------------------------------- queue/admit

def test_queue_admission_control_and_priority():
    q = RequestQueue(max_size=2)
    r = [Request(request_id=i, prompt_ids=np.array([1]), max_new_tokens=4,
                 eos_token_id=None, priority=p)
         for i, p in [(0, 0), (1, 5), (2, 0)]]
    q.push(r[0])
    q.push(r[1])
    with pytest.raises(QueueFull):
        q.push(r[2])
    q.push(r[2], force=True)      # preemption path bypasses the cap
    assert q.pop().request_id == 1   # highest priority first
    assert q.pop().request_id == 0   # then FIFO
    assert q.pop().request_id == 2


def test_infeasible_request_rejected(model):
    cfg = SchedulerConfig(max_num_seqs=2, max_seq_len=32, block_size=8,
                          num_blocks=2)  # pool caps at 16 tokens
    sched = ContinuousBatchingScheduler(model, cfg)
    with pytest.raises(ValueError):
        sched.add_request(np.arange(12), max_new_tokens=8)  # 20 > 16
    with pytest.raises(ValueError):
        sched.add_request(np.arange(30), max_new_tokens=8)  # > window


# ------------------------------------------------------ oracle equivalence

def test_scheduler_matches_eager_ragged8(model):
    """8 ragged requests through a 3-slot grid == per-request eager greedy,
    token for token (continuous batching must not change any sequence)."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1000, int(n))
               for n in rng.integers(4, 14, 8)]
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=3, max_seq_len=64, block_size=8,
                               max_new_tokens=5))
    outs = sched.generate(prompts, max_new_tokens=5)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _eager_oracle(model, p, 5))
    m = sched.metrics.snapshot()
    assert m["requests_finished"] == 8
    assert m["generated_tokens"] == 40
    assert m["free_blocks"] == m["total_blocks"]  # all KV returned


def test_scheduler_preemption_resume_matches_eager(model):
    """KV pool sized so both sequences admit but cannot both finish: the
    younger one is preempted mid-decode, resumed via recompute, and still
    matches its uninterrupted eager decode exactly."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1000, 10), rng.integers(0, 1000, 9)]
    cfg = SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=4,
                          num_blocks=6, max_new_tokens=8)
    sched = ContinuousBatchingScheduler(model, cfg)
    outs = sched.generate(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _eager_oracle(model, p, 8))
    m = sched.metrics.snapshot()
    assert m["preemptions"] >= 1, "pool was sized to force a preemption"
    assert m["prefills"] >= 3      # 2 admissions + >=1 resume recompute
    assert m["free_blocks"] == m["total_blocks"]


def test_scheduler_eos_trims(model):
    # seed 1's greedy stream has distinct tokens mid-stream (needed below);
    # fully-degenerate streams (tiny model fixed points) can't test trimming
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 1000, 8)
    base = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8,
                               max_new_tokens=6)).generate([prompt])[0]
    gen = base[len(prompt):]
    # "eos" = the first mid-stream token NOT seen earlier in the stream, so
    # the run must stop exactly there (a repeated token would stop sooner)
    k = next(i for i in range(1, len(gen)) if gen[i] not in gen[:i])
    eos = int(gen[k])
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8,
                               max_new_tokens=6))
    rid = sched.add_request(prompt, eos_token_id=eos)
    out = sched.run()[rid]
    assert out.finish_reason == "eos"
    assert out.generated_ids[-1] == eos
    assert len(out.generated_ids) == k + 1
    np.testing.assert_array_equal(out.token_ids, base[:len(prompt) + k + 1])


def test_no_recompile_across_admissions(model):
    """Steady state must be zero recompiles: later admissions (same prompt
    buckets) and a whole second workload reuse the same jit programs —
    pinned through the process-wide CompileTracker (the observability
    surface every layer reports compiles into), with the program-cache
    count kept as a cross-check."""
    rng = np.random.default_rng(3)
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=3, max_seq_len=64, block_size=8,
                               max_new_tokens=4))
    sched.generate([rng.integers(0, 1000, int(n))
                    for n in rng.integers(4, 14, 5)], max_new_tokens=4)
    programs = sched.num_programs()
    stats = sched.compile_stats()
    # warmup compiled exactly the tracked programs: one prefill bucket
    # (<=16) + one decode step = exactly two compiles of the slot step
    assert stats["compiles"] == programs == 2
    sched.mark_steady()        # further compiles are RecompileStorm warnings
    sched.generate([rng.integers(0, 1000, int(n))
                    for n in rng.integers(4, 14, 6)], max_new_tokens=4)
    stats = sched.compile_stats()
    assert stats["steady_state_recompiles"] == 0
    assert stats["compiles"] == 2
    assert sched.num_programs() == programs


# -------------------------------------------- streaming / metrics / spans

def test_streaming_callbacks_and_latency_metrics(model):
    rng = np.random.default_rng(4)
    got = []
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8))
    rid = sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=4,
                            on_token=lambda r, t: got.append((r, t)))
    out = sched.run()[rid]
    assert [t for _, t in got] == list(out.generated_ids)
    assert all(r == rid for r, _ in got)
    assert out.ttft_s is not None and out.ttft_s > 0
    assert out.tpot_s is not None and out.tpot_s > 0
    snap = sched.metrics.snapshot()
    assert snap["ttft_s"]["count"] == 1 and snap["tpot_s"]["count"] == 1


def test_stream_iterator_yields_all_tokens(model):
    rng = np.random.default_rng(7)
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8))
    rids = [sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=3)
            for _ in range(3)]
    events = list(sched.stream())
    outs = {rid: sched._finished[rid] for rid in rids}
    for rid in rids:
        toks = [t for r, t in events if r == rid]
        assert toks == list(outs[rid].generated_ids)


def test_profiler_records_serving_spans(model):
    from paddle_tpu.profiler import Profiler

    rng = np.random.default_rng(5)
    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8))
    prof = Profiler(timer_only=False)
    prof.start()
    sched.generate([rng.integers(0, 1000, 6)], max_new_tokens=3)
    prof.stop()
    report = prof.summary()
    assert "serving spans" in report
    assert "serving.prefill" in report
    assert "serving.decode_step" in report


# ------------------------------------------------- inference Config bridge

def test_inference_config_bridges_to_scheduler_config():
    from paddle_tpu.inference import Config

    cfg = Config()
    cfg.enable_memory_optim(False)
    cfg.enable_low_precision("bfloat16")
    sc = cfg.to_scheduler_config(max_num_seqs=4)
    assert sc.enable_preemption is False     # memory_optim wired through
    assert sc.cache_dtype == "bfloat16"      # precision knob wired through
    assert sc.max_num_seqs == 4              # overrides win

    sc2 = Config().to_scheduler_config()
    assert sc2.enable_preemption is True     # untouched default
    assert sc2.cache_dtype == "float32"


# ------------------------------------------------------ generation helpers

def test_trim_at_eos_helper():
    from paddle_tpu.models.generation import trim_at_eos

    p, g = np.array([1, 2]), np.array([3, 9, 4, 9])
    np.testing.assert_array_equal(trim_at_eos(p, g, 9), [1, 2, 3, 9])
    np.testing.assert_array_equal(trim_at_eos(p, g, None), [1, 2, 3, 9, 4, 9])
    np.testing.assert_array_equal(trim_at_eos(p, g, 7), [1, 2, 3, 9, 4, 9])


def test_eager_generate_streams_tokens(model):
    rng = np.random.default_rng(6)
    ids = rng.integers(0, 1000, (2, 5))
    steps = []
    out = model.generate(paddle.to_tensor(ids.astype(np.int64)),
                         max_new_tokens=3, temperature=0.0,
                         on_token=lambda t: steps.append(t))
    out_np = np.asarray(out.numpy())
    assert len(steps) == 3
    np.testing.assert_array_equal(np.stack(steps, 1), out_np[:, 5:])


# ------------------------------------------------------- serve_bench smoke

def test_serve_bench_smoke_writes_artifact(tmp_path):
    """Fast offline load check; writes BENCH_serving_smoke.json so the perf
    axis has a serving trajectory artifact every round."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)

    out = tmp_path / "BENCH_serving_smoke.json"
    artifact = sb.main(["--smoke", "--out", str(out)])
    on_disk = json.loads(out.read_text())
    assert on_disk["bench"] == "serving_continuous_batching"
    # Prometheus text export lands alongside the JSON and parses back
    from paddle_tpu.observability import parse_prometheus_text

    prom = parse_prometheus_text(
        (tmp_path / "BENCH_serving_smoke.prom").read_text())
    assert (prom["serving_generated_tokens"]["value"]
            == on_disk["metrics"]["generated_tokens"])
    assert (prom["serving_ttft_seconds"]["count"]
            == on_disk["metrics"]["ttft_s"]["count"])
    m = artifact["metrics"]
    assert m["requests_finished"] == artifact["config"]["num_requests"]
    assert m["tokens_per_s"] > 0
    assert m["ttft_s"]["count"] == m["requests_finished"]
    assert 0.0 <= m["kv_utilization"] <= 1.0
    assert artifact["compiled_programs"] <= 3
    # the round artifact the driver tracks (repo root, default path)
    root_art = os.path.join(REPO, "BENCH_serving_smoke.json")
    with open(root_art, "w") as f:
        json.dump(on_disk, f, indent=2)


# ------------------------------------------- fault-backoff lock regression

def test_fault_backoff_releases_engine_lock(model):
    """FIXED by this PR (found by graft_lint's blocking-under-lock rule):
    ``_absorb_step_fault`` backed off with ``time.sleep`` while holding
    ``_elock``, so every ``add_request``/``cancel``/``shutdown`` stalled
    behind a fault backoff. The backoff is now ``_elock.wait`` — a
    Condition wait releases the engine lock while sleeping and wakes
    early on ``notify_all``."""
    import threading
    import time

    from paddle_tpu.resilience.faults import InjectedFault

    sched = ContinuousBatchingScheduler(
        model, SchedulerConfig(max_num_seqs=2, max_seq_len=32, block_size=8,
                               retry_backoff_s=5.0))
    got_lock = threading.Event()
    release_times = []

    def contender():
        with sched._elock:
            got_lock.set()
            release_times.append(time.perf_counter())
            sched._elock.notify_all()   # wake the backoff early

    t = threading.Thread(target=contender, daemon=True)
    exc = InjectedFault("serving.decode_step", 1, kind="transient")
    t0 = time.perf_counter()
    with sched._elock:
        t.start()
        failed = sched._absorb_step_fault(exc, running=[], attempt=0)
        absorbed_at = time.perf_counter()
    t.join(timeout=10)
    assert failed == []
    # the contender acquired the lock DURING the backoff (with the old
    # sleep-under-lock it could not run until after absorb returned), and
    # its notify_all cut the 1 s capped wait short
    assert got_lock.is_set()
    assert release_times and release_times[0] <= absorbed_at
    assert absorbed_at - t0 < 0.9, (
        f"backoff held the engine lock for {absorbed_at - t0:.2f}s")
