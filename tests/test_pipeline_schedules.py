"""Round-2 pipeline schedule tests (VERDICT #3):
- schedule tables (FThenB / 1F1B / zero-bubble): dependency validity, memory
  high-water (1F1B < FThenB), bubble (ZB < 1F1B), tick-unit formulas
  (VPP < GPipe);
- schedule-table SPMD engine: grads match jax.grad of the unpipelined loss;
- interleaved (VPP) pipeline: output matches sequential layer stack;
- PipelineLayer: real partition + shared embeddings + train_batch on the
  8-device mesh matching a non-pipelined step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.fleet.pipeline_schedules import (
    B_OP,
    F_OP,
    IDLE,
    W_OP,
    PipelineSchedule,
    gpipe_tick_units,
    interleave_params,
    make_pipeline_schedule,
    schedule_pipeline_grads,
    spmd_pipeline_interleaved,
    vpp_tick_units,
)

S, M = 4, 8


def _mesh():
    devs = np.asarray(jax.devices()[:S])
    return Mesh(devs.reshape(S), axis_names=("pp",))


def _check_dependencies(sched: PipelineSchedule):
    """F(s,m) strictly after F(s-1,m); B(s,m) after B(s+1,m) (or after own F
    on the last stage); W after own B. Message latency: 1 tick."""
    T, S_ = sched.op.shape
    f_t = {}
    b_t = {}
    for t in range(T):
        for s in range(S_):
            op, m = int(sched.op[t, s]), int(sched.slot[t, s])
            if op == F_OP:
                if s > 0:
                    assert (s - 1, m) in f_t and f_t[(s - 1, m)] < t, (s, m, t)
                f_t[(s, m)] = t
            elif op == B_OP:
                assert (s, m) in f_t and f_t[(s, m)] <= t
                if s < S_ - 1:
                    assert (s + 1, m) in b_t and b_t[(s + 1, m)] < t
                b_t[(s, m)] = t
            elif op == W_OP:
                assert (s, m) in b_t and b_t[(s, m)] <= t
    # completeness: every (s, m) ran F and B
    for s in range(S_):
        for m in range(sched.num_microbatches):
            assert (s, m) in f_t and (s, m) in b_t


def test_schedule_tables_valid():
    for policy in ("FThenB", "1F1B", "zero_bubble"):
        sched = make_pipeline_schedule(S, M, policy)
        _check_dependencies(sched)
        if sched.split_bw:
            n_w = (sched.op == W_OP).sum()
            assert n_w == S * M  # every B has a matching W


def test_1f1b_memory_beats_fthenb():
    ft = make_pipeline_schedule(S, M, "FThenB")
    ob = make_pipeline_schedule(S, M, "1F1B")
    assert ob.peak_in_flight() < ft.peak_in_flight()
    assert ft.peak_in_flight() == M
    assert ob.peak_in_flight() == S  # stage 0 holds at most S


def test_zero_bubble_fills_bubbles():
    ob = make_pipeline_schedule(S, M, "1F1B")
    zb = make_pipeline_schedule(S, M, "zero_bubble")
    assert zb.bubble_fraction() < ob.bubble_fraction()


def test_vpp_tick_units_beat_gpipe():
    for V in (2, 4):
        assert vpp_tick_units(S, M, V) < gpipe_tick_units(S, M, V)


def _stack_params(L, D, key):
    return jax.random.normal(key, (L, D, D), jnp.float32) * (1.0 / np.sqrt(D))


def _block(p, h):
    return jnp.tanh(h @ p)


def _loss(h, y):
    return jnp.mean((h - y) ** 2)


@pytest.mark.parametrize("policy", ["FThenB", "1F1B", "zero_bubble"])
def test_engine_grads_match_autodiff(policy):
    mesh = _mesh()
    L, D, B = S, 8, M * 2
    w = _stack_params(L, D, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(2), (B, D), jnp.float32)
    w = jax.device_put(w, NamedSharding(mesh, P("pp")))

    sched = make_pipeline_schedule(S, M, policy)
    loss, grads = jax.jit(
        lambda w_, x_, y_: schedule_pipeline_grads(
            _block, _loss, w_, x_, y_, mesh=mesh, schedule=sched)
    )(w, x, y)

    def ref_loss(w_, x_, y_):
        h = x_
        for i in range(L):
            h = _block(w_[i], h)
        # engine averages per-microbatch losses; each microbatch loss is a
        # mean over its rows, so with equal microbatches this equals the
        # mean over per-microbatch means
        hs = h.reshape(M, B // M, D)
        ys = y_.reshape(M, B // M, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(w, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


def test_interleaved_matches_sequential():
    mesh = _mesh()
    V = 2
    L, D, B = S * V, 8, M * 2
    w = _stack_params(L, D, jax.random.PRNGKey(3))
    x = jax.random.normal(jax.random.PRNGKey(4), (B, D), jnp.float32)

    def ref(w_, x_):
        h = x_
        for i in range(L):
            h = _block(w_[i], h)
        return h

    w_perm = interleave_params(w, S, V)
    w_sh = jax.device_put(w_perm, NamedSharding(mesh, P("pp")))
    out = jax.jit(lambda w_, x_: spmd_pipeline_interleaved(
        _block, w_, x_, mesh=mesh, num_microbatches=M,
        num_virtual_stages=V))(w_sh, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(w, x)),
                               rtol=2e-5, atol=1e-5)


def test_interleaved_grads_flow():
    mesh = _mesh()
    V = 2
    L, D, B = S * V, 8, M
    w = interleave_params(_stack_params(L, D, jax.random.PRNGKey(5)), S, V)
    w = jax.device_put(w, NamedSharding(mesh, P("pp")))
    x = jax.random.normal(jax.random.PRNGKey(6), (B, D), jnp.float32)

    def loss(w_):
        y = spmd_pipeline_interleaved(_block, w_, x, mesh=mesh,
                                      num_microbatches=M,
                                      num_virtual_stages=V)
        return jnp.mean(y ** 2)

    lv, g = jax.jit(jax.value_and_grad(loss))(w)
    assert np.isfinite(float(lv))
    assert float(jnp.abs(g).sum()) > 0


def test_hybrid_tp_pp_schedule_engine():
    """Fleet HybridParallel layout (BASELINE config #4 shape): 2 pipeline
    stages x 4-way tensor parallel on one 2x4 mesh. Megatron MLP blocks
    (column-sharded w1, row-sharded w2, psum over mp) run inside the 1F1B
    schedule engine; loss and grads must match the unsharded reference."""
    S_pp, mp = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(S_pp, mp),
                ("pp", "mp"))
    D, H, M_mb, B = 8, 16, 4, 8
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(0), 4)
    w1 = jax.random.normal(k1, (S_pp, D, H), jnp.float32) * 0.3
    w2 = jax.random.normal(k2, (S_pp, H, D), jnp.float32) * 0.3
    x = jax.random.normal(k3, (B, D), jnp.float32)
    y = jax.random.normal(k4, (B, D), jnp.float32)

    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        megatron_identity,
        megatron_reduce,
    )

    def block_mp(p, h):
        # megatron MLP: f(identity-fwd/allreduce-bwd) at the input, column
        # shard -> gelu -> row shard, g(allreduce-fwd/identity-bwd) at the
        # output — the reference's _c_identity/_c_allreduce conjugate pair
        a, b = p
        h = megatron_identity(h, "mp")
        hidden = jax.nn.gelu(h @ a)          # [mb, H/mp] local
        out = hidden @ b                     # partial [mb, D]
        return megatron_reduce(out, "mp")

    def block_ref(p, h):
        a, b = p
        return jnp.einsum("bh,hd->bd", jax.nn.gelu(h @ a), b)

    sched = make_pipeline_schedule(S_pp, M_mb, "1F1B")
    w1_sh = jax.device_put(w1, NamedSharding(mesh, P("pp", None, "mp")))
    w2_sh = jax.device_put(w2, NamedSharding(mesh, P("pp", "mp", None)))

    loss, (g1, g2) = jax.jit(
        lambda a, b, x_, y_: schedule_pipeline_grads(
            block_mp, _loss, (a, b), x_, y_, mesh=mesh, schedule=sched,
            param_specs=(P("pp", None, "mp"), P("pp", "mp", None)))
    )(w1_sh, w2_sh, x, y)

    def ref_loss(a, b, x_, y_):
        h = x_
        for i in range(S_pp):
            h = block_ref((a[i], b[i]), h)
        hs = h.reshape(M_mb, B // M_mb, D)
        ys = y_.reshape(M_mb, B // M_mb, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, (ref_g1, ref_g2) = jax.value_and_grad(
        ref_loss, argnums=(0, 1))(w1, w2, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(ref_g1),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(ref_g2),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------------- PipelineLayer real


def test_pipeline_layer_partition_and_shared():
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.pipeline import (
        LayerDesc,
        PipelineLayer,
        SharedLayerDesc,
    )

    descs = (
        [SharedLayerDesc("emb", nn.Linear, in_features=4, out_features=8)]
        + [LayerDesc(nn.Linear, in_features=8, out_features=8)
           for _ in range(5)]
        + [SharedLayerDesc("emb", nn.Linear, in_features=4, out_features=8)]
        + [LayerDesc(nn.Linear, in_features=8, out_features=8)]
    )
    pl = PipelineLayer(descs, num_stages=4)
    # partition covers all layers, in order, exactly once
    got = [l for s in range(4) for l in pl.get_stage_layers(s)]
    assert len(got) == len(descs)
    # shared key 'emb' built once: both entries are the same object
    shared = pl.shared_weight_infos()["emb"]
    assert shared[0][1] is shared[1][1]
    # distinct params: 6 unique linears x2 (w, b)
    assert len(pl.parameters()) == 7 * 2


def test_gpt_through_partitioned_pipeline_layer():
    """GPT train_batch through a real PipelineLayer partition: embeddings on
    stage 0, decoder blocks in the middle, final-LN+head+CE on the last stage
    (VERDICT #3 done-criterion)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.pipeline import LayerDesc, PipelineLayer
    from paddle_tpu.models.gpt import (
        GPTDecoderLayer,
        GPTEmbeddings,
        gpt_tiny,
    )

    cfg = gpt_tiny(hidden_size=16, num_layers=4, num_heads=2, vocab_size=32,
                   max_position_embeddings=16)

    class Head(nn.Layer):
        def __init__(self, cfg):
            super().__init__()
            self.ln = nn.LayerNorm(cfg.hidden_size)
            self.proj = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                  bias_attr=False)

        def forward(self, h):
            return self.proj(self.ln(h))

    class CE(nn.Layer):
        def forward(self, logits, labels):
            import paddle_tpu.nn.functional as F
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1])).mean()

    paddle.framework.random.seed(11)
    descs = ([LayerDesc(GPTEmbeddings, cfg)]
             + [LayerDesc(GPTDecoderLayer, cfg) for _ in range(cfg.num_layers)]
             + [LayerDesc(Head, cfg)])
    pl = PipelineLayer(descs, num_stages=S, loss_fn=CE())
    o = opt.AdamW(learning_rate=1e-3, parameters=pl.parameters())

    rng2 = np.random.default_rng(1)
    ids = rng2.integers(0, cfg.vocab_size, (M, 8)).astype(np.int32)
    labels = rng2.integers(0, cfg.vocab_size, (M, 8)).astype(np.int32)
    mesh = _mesh()
    losses = []
    for _ in range(3):
        loss = pl.train_batch(
            (paddle.to_tensor(ids), paddle.to_tensor(labels)), o,
            mesh=mesh, num_microbatches=4)
        losses.append(float(loss.numpy()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # training moves


def test_pipeline_layer_train_batch_matches_single():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.pipeline import (
        LayerDesc,
        PipelineLayer,
    )

    rng = np.random.default_rng(0)
    D = 8
    xs = rng.normal(size=(M, D)).astype(np.float32)
    ys = rng.normal(size=(M, D)).astype(np.float32)

    def build(seed):
        paddle.framework.random.seed(seed)
        descs = [LayerDesc(nn.Linear, in_features=D, out_features=D)
                 for _ in range(S)]
        return PipelineLayer(descs, num_stages=S, loss_fn=nn.MSELoss())

    mesh = _mesh()
    pl = build(7)
    ref = build(7)  # same seed -> same init
    for p, q in zip(pl.parameters(), ref.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy())

    o1 = opt.SGD(learning_rate=0.1, parameters=pl.parameters())
    loss = pl.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), o1,
                          mesh=mesh, num_microbatches=4)

    # reference: plain eager forward + backward on the full batch,
    # averaging per-microbatch losses like the pipeline does
    o2 = opt.SGD(learning_rate=0.1, parameters=ref.parameters())
    mb = M // 4
    total = None
    for i in range(4):
        out = ref.forward(paddle.to_tensor(xs[i * mb:(i + 1) * mb]))
        li = nn.MSELoss()(out, paddle.to_tensor(ys[i * mb:(i + 1) * mb]))
        total = li if total is None else total + li
    total = total / 4
    total.backward()
    o2.step()
    o2.clear_grad()

    np.testing.assert_allclose(float(loss.numpy()), float(total.numpy()),
                               rtol=1e-5)
    for p, q in zip(pl.parameters(), ref.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), rtol=1e-4,
                                   atol=1e-5)


def test_hetero_train_batch_shards_exclusive_params():
    """VERDICT r3 #10: the heterogeneous PipelineLayer path must NOT
    replicate stage weights — each device holds only its own stage's flat
    buffer (1/S of the exclusive total, up to padding) plus the shared
    (tied) params, which replicate by design like the reference's
    SharedLayerDesc pair."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.pipeline import (
        LayerDesc,
        PipelineLayer,
    )

    rng = np.random.default_rng(3)
    D = 16
    xs = rng.normal(size=(M, D)).astype(np.float32)
    ys = rng.normal(size=(M, D)).astype(np.float32)
    paddle.framework.random.seed(5)
    descs = [LayerDesc(nn.Linear, in_features=D, out_features=D)
             for _ in range(S)]
    pl = PipelineLayer(descs, num_stages=S, loss_fn=nn.MSELoss())
    o = opt.SGD(learning_rate=0.05, parameters=pl.parameters())
    loss = pl.train_batch((paddle.to_tensor(xs), paddle.to_tensor(ys)), o,
                          mesh=_mesh(), num_microbatches=4)
    assert np.isfinite(float(loss.numpy()))

    lay = pl._last_param_layout
    total = lay["exclusive_total"] * 4
    per_dev = lay["per_device_bytes"]
    # per-device exclusive bytes ~= total/S (equal stages here: exact)
    assert per_dev * S <= total * 1.25, lay
    assert per_dev <= total // S + 4 * 128, lay
    assert lay["stacked_spec"] == ("pp",)
    # no shared layers in this model
    assert lay["shared_bytes"] == 0


def test_dp_tp_pp_composed_in_one_program():
    """r3: all THREE axes — dp x tp x pp — through the 1F1B schedule engine
    in ONE shard_map program on a 2x2x2 mesh; loss AND grads match the
    unsharded reference (dp shards microbatch rows, megatron blocks shard
    inside stages, stages ride the pp ring)."""
    S_pp, mp, dp = 2, 2, 2
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(S_pp, mp, dp),
                ("pp", "mp", "dp"))
    D, H, M_mb, B = 8, 16, 2, 8
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(1), 4)
    w1 = jax.random.normal(k1, (S_pp, D, H), jnp.float32) * 0.3
    w2 = jax.random.normal(k2, (S_pp, H, D), jnp.float32) * 0.3
    x = jax.random.normal(k3, (B, D), jnp.float32)
    y = jax.random.normal(k4, (B, D), jnp.float32)

    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        megatron_identity,
        megatron_reduce,
    )

    def block_mp(p, h):
        a, b = p
        h = megatron_identity(h, "mp")
        hidden = jax.nn.gelu(h @ a)
        return megatron_reduce(hidden @ b, "mp")

    def block_ref(p, h):
        a, b = p
        return jnp.einsum("bh,hd->bd", jax.nn.gelu(h @ a), b)

    sched = make_pipeline_schedule(S_pp, M_mb, "1F1B")
    w1_sh = jax.device_put(w1, NamedSharding(mesh, P("pp", None, "mp")))
    w2_sh = jax.device_put(w2, NamedSharding(mesh, P("pp", "mp", None)))

    loss, (g1, g2) = jax.jit(
        lambda a, b, x_, y_: schedule_pipeline_grads(
            block_mp, _loss, (a, b), x_, y_, mesh=mesh, schedule=sched,
            param_specs=(P("pp", None, "mp"), P("pp", "mp", None)),
            dp_axis="dp")
    )(w1_sh, w2_sh, x, y)

    def ref_loss(a, b, x_, y_):
        h = x_
        for i in range(S_pp):
            h = block_ref((a[i], b[i]), h)
        hs = h.reshape(M_mb, B // M_mb, D)
        ys = y_.reshape(M_mb, B // M_mb, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, (ref_g1, ref_g2) = jax.value_and_grad(
        ref_loss, argnums=(0, 1))(w1, w2, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(ref_g1),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(ref_g2),
                               rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------- ZB_OPT (r4)


def _weighted_wall(sched):
    cost = {IDLE: 0.0, F_OP: 1.0, B_OP: 2.0, W_OP: 1.0}
    return sum(
        max(max(cost[int(sched.op[t, s])]
                for s in range(sched.num_stages)), 1.0)
        for t in range(sched.num_ticks))


@pytest.mark.parametrize("cfg", [(2, 4), (2, 6), (2, 8), (3, 4), (3, 6),
                                 (4, 4)])
def test_zb_opt_beats_greedy_wall(cfg):
    """r4 (VERDICT weak #5): the exact min-wall search strictly improves on
    the greedy ZB-H1 placement (it aligns cost-2 B ticks across stages,
    which the greedy cannot). r4 late: the A* heuristic extends exactness
    to 4-stage meshes (S4 M4: 24 vs greedy 25; S4 M8 offline: 38 vs 45)."""
    S_, M_ = cfg
    opt = make_pipeline_schedule(S_, M_, "ZB_OPT")
    greedy = make_pipeline_schedule(S_, M_, "ZBH1")
    assert opt.policy == "ZB_OPT"
    assert opt.split_bw
    _check_dependencies(opt)
    assert _weighted_wall(opt) < _weighted_wall(greedy), (
        _weighted_wall(opt), _weighted_wall(greedy))


def test_zb_opt_falls_back_when_state_space_large():
    # combos**S guard: instantly-greedy for clearly-intractable spaces
    big = make_pipeline_schedule(4, 12, "ZB_OPT")
    assert big.policy in ("ZBH1",)  # greedy fallback, still valid
    _check_dependencies(big)
    # the in-search expansion cap also terminates cleanly (None -> greedy)
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        _optimal_zb_schedule,
    )

    assert _optimal_zb_schedule(4, 8, state_cap=100) is None


def test_zb_opt_engine_grads_match_autodiff():
    """The searched schedule runs the real engine: grads == jax.grad of
    the unpipelined loss on a 2-stage mesh."""
    S_, M_ = 2, 6
    mesh = Mesh(np.asarray(jax.devices()[:S_]).reshape(S_),
                axis_names=("pp",))
    L, D, B = S_, 8, M_ * 2
    w = _stack_params(L, D, jax.random.PRNGKey(3))
    x = jax.random.normal(jax.random.PRNGKey(4), (B, D), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(5), (B, D), jnp.float32)
    w = jax.device_put(w, NamedSharding(mesh, P("pp")))

    sched = make_pipeline_schedule(S_, M_, "ZB_OPT")
    assert sched.policy == "ZB_OPT"
    loss, grads = jax.jit(
        lambda w_, x_, y_: schedule_pipeline_grads(
            _block, _loss, w_, x_, y_, mesh=mesh, schedule=sched)
    )(w, x, y)

    def ref_loss(w_, x_, y_):
        h = x_
        for i in range(L):
            h = _block(w_[i], h)
        hs = h.reshape(M_, B // M_, D)
        ys = y_.reshape(M_, B // M_, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(w, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------- ZB-V


def _check_zbv_dependencies(sched):
    """F(v,m) strictly after F(v-1,m); B(v,m) after B(v+1,m) (after own F
    at the last virtual stage); W after own B; one op per device per tick.
    Virtual stage of (device, chunk): v = d if chunk 0 else 2S-1-d."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import ZBVSchedule

    assert isinstance(sched, ZBVSchedule)
    T, S_ = sched.op.shape
    V = 2 * S_
    f_t, b_t, w_t = {}, {}, {}
    for t in range(T):
        for d in range(S_):
            op = int(sched.op[t, d])
            c = int(sched.chunk[t, d])
            m = int(sched.slot[t, d])
            v = d if c == 0 else 2 * S_ - 1 - d
            if op == F_OP:
                if v > 0:
                    assert (v - 1, m) in f_t and f_t[(v - 1, m)] < t, (v, m, t)
                f_t[(v, m)] = t
            elif op == B_OP:
                assert (v, m) in f_t and f_t[(v, m)] < t
                if v < V - 1:
                    assert (v + 1, m) in b_t and b_t[(v + 1, m)] < t
                b_t[(v, m)] = t
            elif op == W_OP:
                assert (v, m) in b_t and b_t[(v, m)] <= t
                w_t[(v, m)] = t
    for v in range(V):
        for m in range(sched.num_microbatches):
            assert (v, m) in f_t and (v, m) in b_t and (v, m) in w_t


@pytest.mark.parametrize("cfg", [(2, 4), (2, 6), (3, 6), (4, 8)])
def test_zbv_schedule_valid_and_memory_bounded(cfg):
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        make_zbv_schedule,
    )

    S_, M_ = cfg
    sched = make_zbv_schedule(S_, M_)
    _check_zbv_dependencies(sched)
    # the V placement's memory claim: per-device in-flight stays in the
    # 1F1B class (admission cap S + a 2-microbatch chunk-1 transient),
    # NOT the 2S of two stacked chunks
    assert sched.peak_in_flight() <= S_ + 2


@pytest.mark.parametrize("cfg", [(2, 6), (3, 6), (4, 8), (4, 16), (8, 16)])
def test_zbv_wall_parity_with_less_memory(cfg):
    """ZB-V's deal vs single-chunk zero-bubble in the lock-step tick
    model: the SAME wall (within one tick) at ~25% LESS peak activation
    memory — an in-flight microbatch pins one CHUNK of activations, not a
    full stage (2 chunks). Measured r4: S4 M8 wall 55 vs 54 chunk-units,
    memory 6 vs 8 chunks; S8 M16 111 vs 110, 12 vs 16."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        make_zbv_schedule,
    )

    S_, M_ = cfg
    zbv = make_zbv_schedule(S_, M_)
    zbh1 = make_pipeline_schedule(S_, M_, "zero_bubble")
    # single-chunk ticks run a WHOLE stage = 2 chunk-units of work;
    # ZB-V ticks are 1 chunk-unit each
    assert zbv.num_ticks <= 2 * zbh1.num_ticks + 2, (
        zbv.num_ticks, 2 * zbh1.num_ticks)
    assert zbv.peak_in_flight() < 2 * zbh1.peak_in_flight(), (
        zbv.peak_in_flight(), 2 * zbh1.peak_in_flight())


def test_zbv_engine_grads_match_autodiff():
    """ZB-V engine on a 2-device mesh (4 virtual stages): loss + grads ==
    jax.grad of the unpipelined stack."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        make_zbv_schedule,
        schedule_pipeline_grads_zbv,
        zbv_params,
        zbv_unpermute,
    )

    S_, M_ = 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:S_]).reshape(S_),
                axis_names=("pp",))
    L, D, B = 2 * S_ * 2, 8, M_ * 2  # 2 layers per chunk
    w_host = _stack_params(L, D, jax.random.PRNGKey(7))
    x = jax.random.normal(jax.random.PRNGKey(8), (B, D), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(9), (B, D), jnp.float32)
    w = jax.device_put(zbv_params(w_host, S_),
                       NamedSharding(mesh, P("pp")))

    sched = make_zbv_schedule(S_, M_)
    loss, grads = jax.jit(
        lambda w_, x_, y_: schedule_pipeline_grads_zbv(
            _block, _loss, w_, x_, y_, mesh=mesh, schedule=sched)
    )(w, x, y)
    grads = zbv_unpermute(grads, S_)

    def ref_loss(w_, x_, y_):
        h = x_
        for i in range(L):
            h = _block(w_[i], h)
        hs = h.reshape(M_, B // M_, D)
        ys = y_.reshape(M_, B // M_, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(w_host, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)


def test_zbv_engine_4stage():
    """Same oracle on a 4-device mesh (8 virtual stages, 1 layer/chunk)."""
    from paddle_tpu.distributed.fleet.pipeline_schedules import (
        make_zbv_schedule,
        schedule_pipeline_grads_zbv,
        zbv_params,
        zbv_unpermute,
    )

    S_, M_ = 4, 8
    mesh = _mesh()
    L, D, B = 2 * S_, 6, M_
    w_host = _stack_params(L, D, jax.random.PRNGKey(10))
    x = jax.random.normal(jax.random.PRNGKey(11), (B, D), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(12), (B, D), jnp.float32)
    w = jax.device_put(zbv_params(w_host, S_),
                       NamedSharding(mesh, P("pp")))

    sched = make_zbv_schedule(S_, M_)
    loss, grads = jax.jit(
        lambda w_, x_, y_: schedule_pipeline_grads_zbv(
            _block, _loss, w_, x_, y_, mesh=mesh, schedule=sched)
    )(w, x, y)
    grads = zbv_unpermute(grads, S_)

    def ref_loss(w_, x_, y_):
        h = x_
        for i in range(L):
            h = _block(w_[i], h)
        hs = h.reshape(M_, B // M_, D)
        ys = y_.reshape(M_, B // M_, D)
        return jnp.mean(jax.vmap(_loss)(hs, ys))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(w_host, x, y)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_g),
                               rtol=1e-4, atol=1e-5)
