"""paddle.geometric parity: segment math, message passing, reindex,
sampling. Expected values come straight from the reference docstring
examples (python/paddle/geometric/math.py, message_passing/send_recv.py,
reindex.py, sampling/neighbors.py)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def _t(a, dtype=None):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


# ---------------------------------------------------------------- segment ops

def test_segment_sum_mean_min_max():
    data = _t([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0], [4.0, 5.0, 6.0]],
              np.float32)
    ids = _t([0, 0, 1], np.int32)
    np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                               [[4, 4, 4], [4, 5, 6]])
    np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                               [[2, 2, 2], [4, 5, 6]])
    np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                               [[1, 2, 1], [4, 5, 6]])
    np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                               [[3, 2, 3], [4, 5, 6]])


def test_segment_empty_segment_fills_zero():
    # id 1 has no rows: every reduce (incl. min/max) yields 0 there, not inf
    data = _t([[1.0, 2.0], [5.0, 6.0]], np.float32)
    ids = _t([0, 2], np.int32)
    for op in (G.segment_sum, G.segment_mean, G.segment_min, G.segment_max):
        out = op(data, ids).numpy()
        assert out.shape == (3, 2)
        np.testing.assert_allclose(out[1], [0.0, 0.0])


def test_segment_sum_grad():
    data = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)
    data.stop_gradient = False
    ids = _t([0, 0, 1], np.int32)
    out = G.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), np.ones((3, 2)))


# ----------------------------------------------------------- message passing

def test_send_u_recv_docstring_examples():
    x = _t([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32)
    src = _t([0, 1, 2, 0], np.int32)
    dst = _t([1, 2, 1, 0], np.int32)
    np.testing.assert_allclose(
        G.send_u_recv(x, src, dst, reduce_op="sum").numpy(),
        [[0, 2, 3], [2, 8, 10], [1, 4, 5]])
    # out_size truncation keeps only the first rows (docstring example 2)
    src2 = _t([0, 2, 0], np.int32)
    dst2 = _t([1, 1, 0], np.int32)
    np.testing.assert_allclose(
        G.send_u_recv(x, src2, dst2, reduce_op="sum", out_size=2).numpy(),
        [[0, 2, 3], [2, 8, 10]])
    # docstring example 3: WITHOUT out_size the output keeps x's row
    # count — the dangling node 2 gets a zero row
    np.testing.assert_allclose(
        G.send_u_recv(x, src2, dst2, reduce_op="sum").numpy(),
        [[0, 2, 3], [2, 8, 10], [0, 0, 0]])


def test_send_u_recv_mean_max_min():
    x = _t([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32)
    src = _t([0, 1, 2, 0], np.int32)
    dst = _t([1, 2, 1, 0], np.int32)
    np.testing.assert_allclose(
        G.send_u_recv(x, src, dst, reduce_op="mean").numpy(),
        [[0, 2, 3], [1, 4, 5], [1, 4, 5]])
    np.testing.assert_allclose(
        G.send_u_recv(x, src, dst, reduce_op="max").numpy(),
        [[0, 2, 3], [2, 6, 7], [1, 4, 5]])
    np.testing.assert_allclose(
        G.send_u_recv(x, src, dst, reduce_op="min").numpy(),
        [[0, 2, 3], [0, 2, 3], [1, 4, 5]])


def test_send_ue_recv_docstring_example():
    x = _t([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32)
    y = _t([1.0, 1.0, 1.0], np.float32)  # feature-broadcast edge term
    src = _t([0, 1, 2, 0], np.int32)
    dst = _t([1, 2, 1, 0], np.int32)
    np.testing.assert_allclose(
        G.send_ue_recv(x, y, src, dst, "add", "sum").numpy(),
        [[1, 3, 4], [4, 10, 12], [2, 5, 6]])


def test_send_ue_recv_per_edge_feature():
    x = _t([[1.0, 1.0], [2.0, 2.0]], np.float32)
    e = _t([10.0, 100.0, 1000.0], np.float32)  # one scalar per edge
    src = _t([0, 1, 0], np.int32)
    dst = _t([0, 0, 1], np.int32)
    np.testing.assert_allclose(
        G.send_ue_recv(x, e, src, dst, "mul", "sum").numpy(),
        [[10 + 200, 10 + 200], [1000, 1000]])


def test_send_uv():
    x = _t([[0, 2, 3], [1, 4, 5], [2, 6, 7]], np.float32)
    y = _t([[1, 1, 1], [2, 2, 2], [3, 3, 3]], np.float32)
    src = _t([0, 1, 2, 0], np.int32)
    dst = _t([1, 2, 1, 0], np.int32)
    np.testing.assert_allclose(
        G.send_uv(x, y, src, dst, "add").numpy(),
        [[2, 4, 5], [4, 7, 8], [4, 8, 9], [1, 3, 4]])


def test_message_passing_grad_flows():
    x = _t(np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32))
    x.stop_gradient = False
    src = _t([0, 1, 2, 3, 0], np.int32)
    dst = _t([1, 0, 3, 2, 2], np.int32)
    out = G.send_u_recv(x, src, dst, reduce_op="sum")
    out.sum().backward()
    # node 0 feeds two edges, others one
    np.testing.assert_allclose(x.grad.numpy()[0], [2, 2, 2])
    np.testing.assert_allclose(x.grad.numpy()[1], [1, 1, 1])


# ------------------------------------------------------------------- reindex

def test_reindex_graph_docstring_example():
    x = _t([0, 1, 2], np.int64)
    neighbors = _t([8, 9, 0, 4, 7, 6, 7], np.int64)
    count = _t([2, 3, 2], np.int32)
    src, dst, nodes = G.reindex_graph(x, neighbors, count)
    np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6])


def test_reindex_heter_graph():
    x = _t([0, 1, 2], np.int64)
    n1 = _t([8, 9, 0, 4, 7, 6, 7], np.int64)
    c1 = _t([2, 3, 2], np.int32)
    n2 = _t([0, 2, 3, 5, 1], np.int64)
    c2 = _t([1, 3, 1], np.int32)
    src, dst, nodes = G.reindex_heter_graph(x, [n1, n2], [c1, c2])
    # shared id space: nodes = [0,1,2, 8,9,4,7,6, 3,5]
    np.testing.assert_array_equal(nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6,
                                                  3, 5])
    np.testing.assert_array_equal(src.numpy()[:7], [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(src.numpy()[7:], [0, 2, 8, 9, 1])
    np.testing.assert_array_equal(dst.numpy(),
                                  [0, 0, 1, 1, 1, 2, 2, 0, 1, 1, 1, 2])


# ------------------------------------------------------------------ sampling

def _csc():
    # graph: neighbors-in-CSC; node n's in-neighbors = row[colptr[n]:colptr[n+1]]
    row = np.asarray([3, 7, 0, 9, 1, 4, 5, 6, 2, 8], np.int64)
    colptr = np.asarray([0, 2, 4, 8, 10, 10], np.int64)
    return _t(row), _t(colptr)


def test_sample_neighbors_full_and_partial():
    row, colptr = _csc()
    nodes = _t([0, 2, 4], np.int64)
    paddle.seed(0)
    neigh, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=-1)
    np.testing.assert_array_equal(cnt.numpy(), [2, 4, 0])
    np.testing.assert_array_equal(neigh.numpy(), [3, 7, 1, 4, 5, 6])

    neigh, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=2)
    np.testing.assert_array_equal(cnt.numpy(), [2, 2, 0])
    # sampled node-2 neighbors are a 2-subset of its true neighbor set
    assert set(neigh.numpy()[2:4]) <= {1, 4, 5, 6}


def test_sample_neighbors_eids_and_reproducibility():
    row, colptr = _csc()
    nodes = _t([2], np.int64)
    eids = _t(np.arange(10), np.int64)
    paddle.seed(7)
    n1, c1, e1 = G.sample_neighbors(row, colptr, nodes, sample_size=3,
                                    eids=eids, return_eids=True)
    # eids pick the same positions as the neighbors
    np.testing.assert_array_equal(row.numpy()[e1.numpy()], n1.numpy())
    paddle.seed(7)
    n2, _, _ = G.sample_neighbors(row, colptr, nodes, sample_size=3,
                                  eids=eids, return_eids=True)
    np.testing.assert_array_equal(n1.numpy(), n2.numpy())


def test_weighted_sample_neighbors_bias():
    row, colptr = _csc()
    nodes = _t([2], np.int64)
    # node 2's neighbors sit at CSC positions 4..8 -> row[4:8] = [1, 4, 5,
    # 6]; weight is per-EDGE (CSC position), heavy mass on position 5 ->
    # neighbor row[5] == 4
    weight = _t(np.asarray([1, 1, 1, 1, 0.001, 1000.0, 0.001, 1, 1, 1],
                           np.float32))
    paddle.seed(1)
    hits = 0
    for _ in range(20):
        neigh, cnt = G.weighted_sample_neighbors(
            row, colptr, weight, nodes, sample_size=1)
        assert cnt.numpy()[0] == 1
        if neigh.numpy()[0] == 4:
            hits += 1
    assert hits >= 18, f"heavy-weight neighbor sampled only {hits}/20"
