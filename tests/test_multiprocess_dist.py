"""Multi-process distributed test tier (VERDICT #4).

Spawns 2 REAL OS processes through paddle_tpu.distributed.launch, each with
its own single-device CPU jax runtime, rendezvoused by jax.distributed —
the reference's TestDistBase pattern (test/legacy_test/test_dist_base.py:952
spawning trainers with env rendezvous and comparing loss curves).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "dist_dp_trainer.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(nproc, log_dir, local_devices=1):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # children pick their own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TEST_LOCAL_DEVICES"] = str(local_devices)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc),
           "--master", f"127.0.0.1:{_free_port()}",
           "--log_dir", log_dir, TRAINER]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=420, cwd=REPO)
    assert proc.returncode == 0, (
        f"launch failed rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}\n" + "".join(
            f"--- {f}:\n" + open(os.path.join(log_dir, f)).read()[-2000:]
            for f in sorted(os.listdir(log_dir))))
    results = []
    for f in sorted(os.listdir(log_dir)):
        for line in open(os.path.join(log_dir, f)):
            line = line.strip()
            if line.startswith("{"):
                results.append(json.loads(line))
    return results


def _single_proc_losses():
    """Same model/data/seed, one process, full batch, 5 steps."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    paddle.framework.random.seed(1234)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    W = rng.normal(size=(8, 1)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    optimizer = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    lossfn = nn.MSELoss()
    losses = []
    for _ in range(5):
        loss = lossfn(model(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


@pytest.mark.slow
def test_multi_device_per_process_collectives(tmp_path):
    """2 processes x 2 local devices: rank semantics stay PER PROCESS —
    all_reduce of (rank+1) must be 3, not a per-device overcount (the
    multi-chip-per-host layout of a real TPU pod)."""
    results = _launch(2, str(tmp_path), local_devices=2)
    assert len(results) == 2, results
    for r in results:
        assert r["world"] == 2
        assert r["allreduce"] == pytest.approx(3.0)
        assert r["gathered"] == [0.0, 10.0]
    by_rank = {r["rank"]: r for r in results}
    np.testing.assert_allclose(by_rank[0]["losses"], by_rank[1]["losses"],
                               rtol=1e-6)


@pytest.mark.slow
def test_two_process_dp_matches_single_proc(tmp_path):
    results = _launch(2, str(tmp_path))
    assert len(results) == 2, results
    by_rank = {r["rank"]: r for r in results}
    assert set(by_rank) == {0, 1}
    for r in results:
        assert r["world"] == 2
        # allreduce of (rank+1): 1 + 2 = 3
        assert r["allreduce"] == pytest.approx(3.0)
        assert r["gathered"] == [0.0, 10.0]
        assert r["broadcast"] == 0.0
    # both ranks agree on the global loss curve
    np.testing.assert_allclose(by_rank[0]["losses"], by_rank[1]["losses"],
                               rtol=1e-6)
    # and it matches the single-process full-batch run (TestDistBase check):
    # avg of half-batch MSE grads == full-batch MSE grad for equal shards
    single = _single_proc_losses()
    np.testing.assert_allclose(by_rank[0]["losses"], single, rtol=2e-4,
                               atol=1e-5)
