"""paddle.utils surface: unique_name, deprecated, require_version,
try_import, run_check (reference python/paddle/utils/__init__.py:15-57),
and the Parameter auto-naming they enable (EagerParamBase parity,
base/framework.py:7629)."""

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.utils import (
    deprecated,
    require_version,
    run_check,
    try_import,
    unique_name,
)


def test_unique_name_generate_and_guard():
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
        assert unique_name.generate("fc") == "fc_1"
        assert unique_name.generate("conv") == "conv_0"
        with unique_name.guard("prefix_"):
            assert unique_name.generate("fc") == "prefix_fc_0"
        # inner guard scoped away: outer counters resume
        assert unique_name.generate("fc") == "fc_2"


def test_unique_name_switch_roundtrip():
    old = unique_name.switch()
    try:
        a = unique_name.generate("x")
        assert a == "x_0"
    finally:
        unique_name.switch(old)


def test_parameters_auto_named_and_distinct():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    names = [p.name for p in m.parameters()]
    assert all(names), names
    assert len(set(names)) == len(names), names


def test_param_attr_name_still_wins():
    from paddle_tpu.nn.param_attr import ParamAttr

    lin = nn.Linear(3, 3, weight_attr=ParamAttr(name="my_weight"))
    assert lin.weight.name == "my_weight"


def test_apply_decay_param_fun_keyed_on_names():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    decay = {p.name for p in m.parameters() if p.ndim > 1}
    opt = paddle.optimizer.AdamW(
        parameters=m.parameters(), weight_decay=0.1,
        apply_decay_param_fun=lambda n: n in decay)
    assert opt._decay_for(m[0].weight) == 0.1
    assert opt._decay_for(m[0].bias) == 0.0


def test_deprecated_decorator_warns_and_annotates():
    @deprecated(update_to="paddle.new_api", since="2.0", reason="renamed")
    def legacy(x):
        """Original doc."""
        return x + 1

    assert "deprecated" in legacy.__doc__
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert legacy(1) == 2
    assert any(issubclass(x.category, DeprecationWarning) for x in w)


def test_deprecated_level2_raises():
    @deprecated(level=2)
    def gone():
        pass

    with pytest.raises(RuntimeError):
        gone()


def test_require_version():
    require_version("0.0.1")
    require_version("0.0.1", "99.0")
    with pytest.raises(Exception, match="required"):
        require_version("99.0")
    with pytest.raises(TypeError):
        require_version(1)
    with pytest.raises(ValueError):
        require_version("not-a-version")


def test_try_import():
    assert try_import("numpy") is np
    with pytest.raises(ImportError, match="pip install"):
        try_import("definitely_not_a_module_xyz")


def test_run_check_multi_device(capsys):
    run_check()
    out = capsys.readouterr().out
    assert "works well on 1" in out
    # conftest forces 8 virtual devices: the DP check must have run
    assert "8" in out
    assert "installed successfully" in out
