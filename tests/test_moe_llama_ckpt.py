"""MoE, LLaMA, fused incubate ops, distributed checkpoint (reference
patterns: test/collective/fleet moe tests, test_fused_rotary_position
_embedding.py, auto_parallel semi_auto_llama.py, test_dist_checkpoint)."""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.incubate.nn import functional as IF


def test_fused_rms_norm_matches_composite(rng):
    x = rng.standard_normal((2, 5, 8)).astype(np.float32)
    w = rng.standard_normal(8).astype(np.float32)
    out = IF.fused_rms_norm(paddle.to_tensor(x), norm_weight=paddle.to_tensor(w))
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


def test_fused_rms_norm_residual(rng):
    x = rng.standard_normal((2, 4)).astype(np.float32)
    r = rng.standard_normal((2, 4)).astype(np.float32)
    out, res = IF.fused_rms_norm(paddle.to_tensor(x),
                                 residual=paddle.to_tensor(r))
    np.testing.assert_allclose(res.numpy(), x + r, rtol=1e-6)


def test_rope_rotation_properties(rng):
    # RoPE preserves norms and is identity at position 0
    q = rng.standard_normal((1, 8, 2, 16)).astype(np.float32)
    qr, _, _ = IF.fused_rotary_position_embedding(paddle.to_tensor(q))
    qr = qr.numpy()
    np.testing.assert_allclose(qr[:, 0], q[:, 0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.linalg.norm(qr, axis=-1), np.linalg.norm(q, axis=-1),
        rtol=1e-4, atol=1e-5)


def test_swiglu(rng):
    x = rng.standard_normal((3, 10)).astype(np.float32)
    out = IF.swiglu(paddle.to_tensor(x))
    a, b = x[:, :5], x[:, 5:]
    ref = a / (1 + np.exp(-a)) * b
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


def test_moe_layer_topk_routing(rng):
    from paddle_tpu.incubate.distributed.models.moe import MoELayer, NaiveGate

    d = 16
    experts = [nn.Linear(d, d) for _ in range(4)]
    moe = MoELayer(d, experts, gate=NaiveGate(d, 4, topk=2),
                   capacity_factor=8.0)  # ample capacity: nothing dropped
    x = paddle.to_tensor(rng.standard_normal((2, 6, d)).astype(np.float32),
                         stop_gradient=False)
    y = moe(x)
    assert y.shape == [2, 6, d]
    paddle.sum(y * y).backward()
    assert moe.gate.gate.weight.grad is not None
    # with k=2 softmax weights, output is a convex combination of 2 experts:
    # check it is not all zeros and grads reach at least one expert
    got = any(e.weight.grad is not None and
              float(np.abs(e.weight.grad.numpy()).sum()) > 0 for e in experts)
    assert got


def test_moe_gshard_aux_loss(rng):
    from paddle_tpu.incubate.distributed.models.moe import GShardGate, MoELayer

    d = 8
    experts = [nn.Linear(d, d) for _ in range(2)]
    moe = MoELayer(d, experts, gate=GShardGate(d, 2))
    x = paddle.to_tensor(rng.standard_normal((1, 8, d)).astype(np.float32))
    _ = moe(x)
    aux = moe.gate.get_loss()
    assert aux is not None and np.isfinite(float(aux.numpy()))


def test_llama_forward_backward(rng):
    from paddle_tpu.models import (
        LlamaForCausalLM,
        LlamaPretrainingCriterion,
        llama_tiny,
    )

    cfg = llama_tiny(num_layers=1)
    m = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32))
    logits = m(ids)
    assert logits.shape == [2, 16, cfg.vocab_size]
    loss = LlamaPretrainingCriterion()(logits, ids)
    loss.backward()
    assert m.llama.layers[0].mlp.gate_proj.weight.grad is not None
    # GQA: kv heads < q heads
    assert cfg.num_key_value_heads == 2 and cfg.num_heads == 4


def test_dist_checkpoint_roundtrip(tmp_path, rng):
    sd = {"w": paddle.to_tensor(rng.standard_normal((4, 4)).astype(np.float32)),
          "nested": {"b": paddle.to_tensor(np.arange(3, dtype=np.float32))}}
    dist.save_state_dict(sd, str(tmp_path))
    sd2 = {"w": paddle.to_tensor(np.zeros((4, 4), np.float32)),
           "nested": {"b": paddle.to_tensor(np.zeros(3, np.float32))}}
    dist.load_state_dict(sd2, str(tmp_path))
    np.testing.assert_allclose(sd2["w"].numpy(), sd["w"].numpy())
    np.testing.assert_allclose(sd2["nested"]["b"].numpy(), [0, 1, 2])


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_dist_checkpoint_reshard_on_load(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh8 = Mesh(np.asarray(jax.devices()).reshape(8), ("x",))
    mesh24 = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("a", "b"))
    src = jax.device_put(
        np.arange(64.0, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh8, P("x")))
    dist.save_state_dict({"w": paddle.Tensor._from_value(src)}, str(tmp_path))
    tgt = jax.device_put(np.zeros((8, 8), np.float32),
                         NamedSharding(mesh24, P("a", "b")))
    t2 = paddle.Tensor._from_value(tgt)
    dist.load_state_dict({"w": t2}, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(t2._value), np.arange(64.0).reshape(8, 8))
    # target sharding preserved
    assert t2._value.sharding.spec == P("a", "b")


def test_dist_checkpoint_bfloat16(tmp_path, rng):
    x = rng.standard_normal((4, 4)).astype(np.float32)
    t = paddle.to_tensor(x).astype("bfloat16")
    dist.save_state_dict({"w": t}, str(tmp_path))
    t2 = paddle.to_tensor(np.zeros((4, 4), np.float32)).astype("bfloat16")
    dist.load_state_dict({"w": t2}, str(tmp_path))
    np.testing.assert_allclose(
        t2.astype("float32").numpy(), t.astype("float32").numpy())


def test_moe_routing_positions_unique(rng):
    # tokens routed to the same expert must land in distinct capacity slots:
    # expert input slot 0 must equal the FIRST token routed there, not a sum
    from paddle_tpu.incubate.distributed.models.moe import MoELayer, NaiveGate
    import paddle_tpu.nn as nn

    d = 4

    class Identity(nn.Layer):
        def forward(self, x):
            return x

    moe = MoELayer(d, [Identity() for _ in range(2)],
                   gate=NaiveGate(d, 2, topk=1), capacity_factor=4.0)
    # force all tokens to expert 0 by zeroing the gate weight and biasing
    moe.gate.gate.weight.set_value(np.zeros((d, 2), np.float32))
    moe.gate.gate.bias.set_value(np.array([10.0, -10.0], np.float32))
    x = rng.standard_normal((1, 3, d)).astype(np.float32)
    y = moe(paddle.to_tensor(x))
    # identity experts + top-1 softmax weight 1.0 -> output == input
    np.testing.assert_allclose(y.numpy(), x, rtol=1e-5, atol=1e-6)


def test_dist_checkpoint_async(tmp_path, rng):
    sd = {"w": paddle.to_tensor(rng.standard_normal((8,)).astype(np.float32))}
    dist.save_state_dict(sd, str(tmp_path), async_save=True)
    dist.checkpoint.wait_async_save()
    sd2 = {"w": paddle.to_tensor(np.zeros(8, np.float32))}
    dist.load_state_dict(sd2, str(tmp_path))
    np.testing.assert_allclose(sd2["w"].numpy(), sd["w"].numpy())
