"""ZeRO sharding stages over the mesh (reference patterns:
test/collective/fleet/dygraph_group_sharded_stage2/3 tests — loss equality
between sharded and unsharded runs, state placement checks)."""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet import topology as topo
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit.api import TrainStep

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices")


def _build(seed=0, lr=1e-2):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))
    optimizer = opt.AdamW(learning_rate=lr, parameters=model.parameters())
    return model, optimizer


def _train(model, optimizer, steps=5):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, a, b: mse(m(a), b), optimizer)
    return [float(step(x, y).numpy()) for _ in range(steps)]


@requires_8
@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_sharded_matches_unsharded_losses(level):
    hcg = topo.HybridCommunicateGroup(dp_degree=8)
    topo.set_hybrid_communicate_group(hcg)
    try:
        m1, o1 = _build()
        ref_losses = _train(m1, o1)

        m2, o2 = _build()
        # init optimizer states eagerly (as TrainStep would) so stage>=1
        # has states to shard
        for p in o2._parameter_list:
            o2._state.setdefault(id(p), o2._init_state(p))
        m2, o2 = group_sharded_parallel(m2, o2, level)
        losses = _train(m2, o2)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)
    finally:
        topo.set_hybrid_communicate_group(None)


@requires_8
def test_stage1_states_actually_sharded():
    from jax.sharding import PartitionSpec as P

    hcg = topo.HybridCommunicateGroup(dp_degree=8)
    topo.set_hybrid_communicate_group(hcg)
    try:
        model, optimizer = _build()
        for p in optimizer._parameter_list:
            optimizer._state.setdefault(id(p), optimizer._init_state(p))
        group_sharded_parallel(model, optimizer, "os")
        # the [16, 32] moment tensors must carry a dp shard
        sharded = 0
        for st in optimizer._state.values():
            for v in st.values():
                if hasattr(v, "sharding") and v.ndim >= 2:
                    if v.sharding.spec != P():
                        sharded += 1
        assert sharded > 0
        # params stay replicated at stage 1
        for p in model.parameters():
            assert p._value.sharding.is_fully_replicated
    finally:
        topo.set_hybrid_communicate_group(None)


@requires_8
def test_stage3_params_sharded_and_training_converges():
    from jax.sharding import PartitionSpec as P

    hcg = topo.HybridCommunicateGroup(dp_degree=8)
    topo.set_hybrid_communicate_group(hcg)
    try:
        model, optimizer = _build(lr=5e-2)
        for p in optimizer._parameter_list:
            optimizer._state.setdefault(id(p), optimizer._init_state(p))
        group_sharded_parallel(model, optimizer, "p_g_os")
        n_sharded = sum(
            1 for p in model.parameters()
            if p._value.ndim >= 2 and p._value.sharding.spec != P())
        assert n_sharded > 0
        losses = _train(model, optimizer, steps=15)
        assert losses[-1] < losses[0] * 0.7
    finally:
        topo.set_hybrid_communicate_group(None)


@requires_8
def test_stage1_with_bf16_master_weights():
    hcg = topo.HybridCommunicateGroup(dp_degree=8)
    topo.set_hybrid_communicate_group(hcg)
    try:
        model, _ = _build()
        optimizer = opt.AdamW(learning_rate=1e-2,
                              parameters=model.parameters(),
                              multi_precision=True)
        model, optimizer = paddle.amp.decorate(model, optimizer, level="O2")
        for p in optimizer._parameter_list:
            optimizer._state.setdefault(id(p), optimizer._init_state(p))
            optimizer._master(p)
        group_sharded_parallel(model, optimizer, "os")
        losses = _train(model, optimizer, steps=10)
        assert losses[-1] < losses[0]
    finally:
        topo.set_hybrid_communicate_group(None)


def test_offload_eager_step_keeps_states_on_host():
    """offload=True: optimizer states + fp32 masters live in pinned_host
    memory and stay there across eager steps; params stay in device memory.
    (reference: group_sharded offload, group_sharded_storage.py)"""
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 8))
    optimizer = opt.AdamW(learning_rate=1e-2, parameters=model.parameters())
    model, optimizer = group_sharded_parallel(model, optimizer, "os",
                                             offload=True)
    assert optimizer._offload
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    mse = nn.MSELoss()
    y = paddle.to_tensor(np.zeros((4, 8), np.float32))
    for _ in range(2):
        loss = mse(model(x), y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
    for st in optimizer._state.values():
        for v in st.values():
            if hasattr(v, "sharding"):
                assert v.sharding.memory_kind == "pinned_host", v.sharding
    for p in model.parameters():
        assert p._value.sharding.memory_kind == "device"


def test_offload_matches_unoffloaded_losses():
    m1, o1 = _build(seed=4)
    ref = _train(m1, o1)
    m2, o2 = _build(seed=4)
    m2, o2 = group_sharded_parallel(m2, o2, "os", offload=True)
    losses = _train(m2, o2)
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)


def test_offload_trainstep_keeps_states_on_host():
    """The compiled TrainStep must return states pinned to host memory so
    the hot loop never migrates them to device residence."""
    m, o = _build(seed=5)
    m, o = group_sharded_parallel(m, o, "os", offload=True)
    _train(m, o, steps=3)
    for p in o._parameter_list:
        st = o._state[id(p)]
        for v in st.values():
            if hasattr(v, "sharding"):
                assert v.sharding.memory_kind == "pinned_host", v.sharding
        assert p._value.sharding.memory_kind == "device"


def test_offload_multi_precision_eager_steps():
    """bf16 params + fp32 host-resident masters: repeated eager steps must
    not rebuild state against the offloaded master (init-once guard)."""
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(8, 8))
    for p in model.parameters():
        p._replace_value(p._value.astype("bfloat16"))
    optimizer = opt.AdamW(learning_rate=1e-2,
                          parameters=model.parameters(),
                          multi_precision=True)
    model, optimizer = group_sharded_parallel(model, optimizer, "os",
                                              offload=True)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    mse = nn.MSELoss()
    y = paddle.to_tensor(np.zeros((4, 8), np.float32))
    for _ in range(3):
        loss = mse(model(x).astype("float32"), y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
    for mv in optimizer._master_weights.values():
        assert mv.sharding.memory_kind == "pinned_host"
    for p in model.parameters():
        assert p._value.sharding.memory_kind == "device"


def test_stage3_param_offload_eager_and_trainstep():
    """Stage-3 offload (r5): PARAMS rest in pinned host memory between
    steps and are streamed to device on demand at forward entry
    (reference group_sharded_storage.py:48,121 convert_cpu); loss-equal
    to the unoffloaded run."""
    hcg = topo.HybridCommunicateGroup(dp_degree=min(8, jax.device_count()))
    topo.set_hybrid_communicate_group(hcg)
    try:
        m1, o1 = _build(seed=21)
        ref = _train(m1, o1)

        m2, o2 = _build(seed=21)
        m2, o2 = group_sharded_parallel(m2, o2, "p_g_os", offload=True)
        assert getattr(o2, "_offload_params", False)
        # parked on host after setup
        for p in m2.parameters():
            assert p._value.sharding.memory_kind == "pinned_host"
        losses = _train(m2, o2)
        np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)
        # still parked after compiled steps
        for p in m2.parameters():
            assert p._value.sharding.memory_kind == "pinned_host"

        # eager path: forward streams params in, step re-parks them
        m3, o3 = _build(seed=21)
        m3, o3 = group_sharded_parallel(m3, o3, "p_g_os", offload=True)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
        mse = nn.MSELoss()
        loss = mse(m3(x), y)
        loss.backward()
        o3.step()
        o3.clear_grad()
        for p in m3.parameters():
            assert p._value.sharding.memory_kind == "pinned_host"
        eager_l0 = float(loss.numpy())
        np.testing.assert_allclose(eager_l0, ref[0], rtol=1e-4)
    finally:
        topo.set_hybrid_communicate_group(None)


def test_stage3_offload_survives_eager_warmup_forward():
    """An eager warmup/eval forward fetches params to device; the first
    compiled TrainStep must STILL bake the recorded pinned-host layout
    into its out_shardings so the hot loop re-parks params (r5 review)."""
    hcg = topo.HybridCommunicateGroup(dp_degree=min(8, jax.device_count()))
    topo.set_hybrid_communicate_group(hcg)
    try:
        m, o = _build(seed=22)
        m, o = group_sharded_parallel(m, o, "p_g_os", offload=True)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
        _ = m(x)  # warmup: params now device-resident
        assert any(p._value.sharding.memory_kind == "device"
                   for p in m.parameters())
        _train(m, o, steps=2)
        for p in m.parameters():
            assert p._value.sharding.memory_kind == "pinned_host", \
                p._value.sharding
    finally:
        topo.set_hybrid_communicate_group(None)
