"""nn.LSTM / GRU / SimpleRNN layer tests (paddle layer API over the
lax.scan recurrence), validated against torch's cuDNN-convention RNNs
(same gate orders / weight layouts)."""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _copy_weights(pt, ours, num_layers, bidirect, gates):
    # identical naming convention: weight_ih_l{n}[_reverse] etc.
    D = 2 if bidirect else 1
    for layer in range(num_layers):
        for d in range(D):
            sfx = f"l{layer}" + ("_reverse" if d else "")
            for nm in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                src = getattr(pt, f"{nm}_{sfx}").detach().numpy()
                getattr(ours, f"{nm}_{sfx}").set_value(src)


@pytest.mark.parametrize("bidirect", [False, True])
def test_lstm_matches_torch(bidirect):
    B, T, I, H, L = 2, 5, 4, 6, 2
    rng = np.random.default_rng(0)
    x = rng.normal(size=(B, T, I)).astype(np.float32)

    pt = torch.nn.LSTM(I, H, num_layers=L, batch_first=True,
                       bidirectional=bidirect)
    ours = nn.LSTM(I, H, num_layers=L,
                   direction="bidirect" if bidirect else "forward")
    _copy_weights(pt, ours, L, bidirect, 4)

    ref, (h_ref, c_ref) = pt(torch.from_numpy(x))
    out, (h, c) = ours(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h.numpy(), h_ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(c.numpy(), c_ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_gru_matches_torch():
    B, T, I, H = 2, 5, 4, 6
    rng = np.random.default_rng(1)
    x = rng.normal(size=(B, T, I)).astype(np.float32)
    pt = torch.nn.GRU(I, H, batch_first=True)
    ours = nn.GRU(I, H)
    _copy_weights(pt, ours, 1, False, 3)
    ref, h_ref = pt(torch.from_numpy(x))
    out, h = ours(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_simple_rnn_matches_torch():
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.default_rng(2)
    x = rng.normal(size=(B, T, I)).astype(np.float32)
    pt = torch.nn.RNN(I, H, batch_first=True, nonlinearity="tanh")
    ours = nn.SimpleRNN(I, H, activation="tanh")
    _copy_weights(pt, ours, 1, False, 1)
    ref, _ = pt(torch.from_numpy(x))
    out, _ = ours(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_lstm_trains():
    paddle.framework.random.seed(0)
    m = nn.LSTM(4, 8)
    head = nn.Linear(8, 1)
    import paddle_tpu.optimizer as opt

    o = opt.Adam(learning_rate=1e-2,
                 parameters=m.parameters() + head.parameters())
    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 6, 4)).astype(np.float32)
    y = rng.normal(size=(8, 1)).astype(np.float32)
    lossfn = nn.MSELoss()
    losses = []
    for _ in range(8):
        out, (h, c) = m(paddle.to_tensor(x))
        pred = head(out[:, -1])
        loss = lossfn(pred, paddle.to_tensor(y))
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < 0.5 * losses[0]


def test_cells_single_step():
    B, I, H = 3, 4, 5
    rng = np.random.default_rng(4)
    x = paddle.to_tensor(rng.normal(size=(B, I)).astype(np.float32))
    cell = nn.LSTMCell(I, H)
    h, (h2, c2) = cell(x)
    assert h.shape == [B, H] and c2.shape == [B, H]
    gcell = nn.GRUCell(I, H)
    h, _ = gcell(x)
    assert h.shape == [B, H]
    scell = nn.SimpleRNNCell(I, H)
    h, _ = scell(x)
    assert h.shape == [B, H]


def test_generic_rnn_and_birnn():
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.default_rng(5)
    x = paddle.to_tensor(rng.normal(size=(B, T, I)).astype(np.float32))
    cell = nn.GRUCell(I, H)
    rnn = nn.RNN(cell)
    out, st = rnn(x)
    assert out.shape == [B, T, H]
    # reverse consistency: BiRNN concat of fw/bw
    bi = nn.BiRNN(nn.GRUCell(I, H), nn.GRUCell(I, H))
    out2, (sf, sb) = bi(x)
    assert out2.shape == [B, T, 2 * H]


def test_tensor_array_ops():
    arr = paddle.create_array()
    t0 = paddle.to_tensor(np.asarray([1.0], np.float32))
    t1 = paddle.to_tensor(np.asarray([2.0], np.float32))
    paddle.array_write(t0, 0, arr)
    paddle.array_write(t1, 3, arr)
    assert int(paddle.array_length(arr).numpy()) == 4
    np.testing.assert_allclose(paddle.array_read(arr, 3).numpy(), [2.0])
    with pytest.raises(IndexError):
        paddle.array_read(arr, 1)


def test_lstm_sequence_length_masking():
    """Variable-length contract vs torch pack_padded_sequence: padded steps
    zeroed in output, final state frozen at each sequence's last valid step."""
    B, T, I, H = 3, 6, 4, 5
    rng = np.random.default_rng(6)
    x = rng.normal(size=(B, T, I)).astype(np.float32)
    lens = np.asarray([6, 3, 1], np.int64)
    x_masked = x.copy()
    for b, l in enumerate(lens):
        x_masked[b, l:] = 0

    pt = torch.nn.LSTM(I, H, batch_first=True)
    ours = nn.LSTM(I, H)
    _copy_weights(pt, ours, 1, False, 4)

    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.from_numpy(x), torch.from_numpy(lens), batch_first=True,
        enforce_sorted=False)
    packed_out, (h_ref, c_ref) = pt(packed)
    ref_out, _ = torch.nn.utils.rnn.pad_packed_sequence(
        packed_out, batch_first=True, total_length=T)

    out, (h, c) = ours(paddle.to_tensor(x),
                       sequence_length=paddle.to_tensor(lens))
    np.testing.assert_allclose(out.numpy(), ref_out.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h.numpy(), h_ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(c.numpy(), c_ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("kind", ["lstm_bidir_2l", "gru", "simple"])
def test_variable_length_other_configs(kind):
    """Masking coverage for the subtle paths: bidirectional/multi-layer
    (reversed time indices + carry freeze) and the non-LSTM scan branch."""
    B, T, I, H = 3, 5, 4, 6
    rng = np.random.default_rng(7)
    x = rng.normal(size=(B, T, I)).astype(np.float32)
    lens = np.asarray([5, 3, 2], np.int64)

    if kind == "lstm_bidir_2l":
        pt = torch.nn.LSTM(I, H, num_layers=2, batch_first=True,
                           bidirectional=True)
        ours = nn.LSTM(I, H, num_layers=2, direction="bidirect")
        _copy_weights(pt, ours, 2, True, 4)
    elif kind == "gru":
        pt = torch.nn.GRU(I, H, batch_first=True)
        ours = nn.GRU(I, H)
        _copy_weights(pt, ours, 1, False, 3)
    else:
        pt = torch.nn.RNN(I, H, batch_first=True, nonlinearity="tanh")
        ours = nn.SimpleRNN(I, H)
        _copy_weights(pt, ours, 1, False, 1)

    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.from_numpy(x), torch.from_numpy(lens), batch_first=True,
        enforce_sorted=False)
    packed_out, _ = pt(packed)
    ref_out, _ = torch.nn.utils.rnn.pad_packed_sequence(
        packed_out, batch_first=True, total_length=T)

    out, _ = ours(paddle.to_tensor(x),
                  sequence_length=paddle.to_tensor(lens))
    np.testing.assert_allclose(out.numpy(), ref_out.detach().numpy(),
                               rtol=1e-3, atol=1e-4)
