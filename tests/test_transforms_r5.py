"""r5 vision transforms closure (reference transforms.py:980 Saturation,
:1022 Hue, :1067 ColorJitter, :1385 RandomAffine, :1650 RandomPerspective,
:1832 RandomErasing) — analytic oracles: saturation-0 = grayscale, hue
half-turn red->cyan, identity affine/perspective = identity, 90-degree
affine = rot90, erase zeroes the region."""

import numpy as np

from paddle_tpu.vision import transforms as T
from paddle_tpu.vision.transforms import functional as F


def _img():
    rng = np.random.default_rng(0)
    return rng.integers(0, 255, (16, 12, 3)).astype(np.uint8)


def test_adjust_saturation_zero_is_grayscale():
    img = _img()
    out = F.adjust_saturation(img, 0.0)
    assert np.ptp(out.astype(np.int32), axis=-1).max() <= 1  # channels equal
    same = F.adjust_saturation(img, 1.0)
    np.testing.assert_allclose(same, img, atol=1)


def test_adjust_hue_half_turn_red_to_cyan():
    red = np.zeros((2, 2, 3), np.uint8)
    red[..., 0] = 255
    cyan = F.adjust_hue(red, 0.5)
    assert cyan[0, 0, 0] < 10 and cyan[0, 0, 1] > 245 and cyan[0, 0, 2] > 245
    back = F.adjust_hue(red, 0.0)
    np.testing.assert_allclose(back, red, atol=1)
    try:
        F.adjust_hue(red, 0.7)
        assert False
    except ValueError:
        pass


def test_affine_identity_and_rot90():
    img = _img()
    ident = F.affine(img, angle=0.0)
    np.testing.assert_array_equal(ident, img)
    sq = img[:12, :12]
    rot = F.affine(sq, angle=90.0, interpolation="nearest")
    # same angle convention as the repo's existing F.rotate
    np.testing.assert_array_equal(rot, F.rotate(sq, 90))
    np.testing.assert_array_equal(rot, np.rot90(sq, -1))


def test_perspective_identity_and_shift():
    img = _img()
    H, W = img.shape[:2]
    corners = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
    ident = F.perspective(img, corners, corners)
    np.testing.assert_array_equal(ident, img)
    # shifting endpoints right by 2 samples source from the left
    shifted = F.perspective(
        img, corners, [(x + 2, y) for x, y in corners])
    np.testing.assert_array_equal(shifted[:, 2:], img[:, :-2])


def test_erase_region():
    img = _img()
    out = F.erase(img, 2, 3, 4, 5, 0)
    assert (out[2:6, 3:8] == 0).all()
    assert (out[:2] == img[:2]).all()
    assert (img[2:6, 3:8] != 0).any()  # not inplace by default


def test_transform_classes_run_and_change_or_preserve():
    import random

    random.seed(0)
    img = _img()
    for t in (T.SaturationTransform(0.4), T.HueTransform(0.2),
              T.ColorJitter(0.3, 0.3, 0.3, 0.2),
              T.RandomAffine(15, translate=(0.1, 0.1), scale=(0.8, 1.2),
                             shear=5),
              T.RandomPerspective(prob=1.0, distortion_scale=0.3),
              T.RandomErasing(prob=1.0)):
        out = t(img)
        assert out.shape == img.shape, type(t).__name__
        assert out.dtype == img.dtype, type(t).__name__
    # prob=0 transforms are identity
    np.testing.assert_array_equal(T.RandomErasing(prob=0.0)(img), img)
    np.testing.assert_array_equal(T.RandomPerspective(prob=0.0)(img), img)
    erased = T.RandomErasing(prob=1.0)(img)
    assert (erased == 0).any()


def test_compose_pipeline_with_new_transforms():
    import random

    random.seed(1)
    pipe = T.Compose([T.Resize(14), T.ColorJitter(0.2, 0.2, 0.2, 0.1),
                      T.RandomErasing(prob=1.0), T.ToTensor()])
    out = pipe(_img())
    assert tuple(out.shape)[0] == 3  # CHW tensor out
