"""Worker for the comm-watchdog drill (run by test_comm_watchdog.py).

Rank 1 rendezvouses, completes one warm-up collective, then DIES.
Rank 0 then enters a second collective that can never complete; the
watchdog must raise CommTimeoutError (or surface the backend's peer error)
instead of hanging forever — the reference CommTaskManager contract.
"""

import os
import sys
import time

import numpy as np


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    coord = os.environ["PADDLE_MASTER"]

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord, num_processes=world,
                               process_id=rank)

    from paddle_tpu.framework import flags as _flags
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import env as _env
    from paddle_tpu.distributed.watchdog import CommTimeoutError

    _env.init_parallel_env()
    _flags.set_flags({"FLAGS_comm_timeout_s": 8.0})

    from paddle_tpu.tensor import Tensor
    import jax.numpy as jnp

    # warm-up collective: both ranks participate
    v = Tensor._from_value(jnp.asarray(np.full((4,), rank + 1, np.float32)))
    dist.all_reduce(v)
    print(f"[rank {rank}] warmup ok: {np.asarray(v.numpy()).tolist()}",
          flush=True)

    if rank == 1:
        print("[rank 1] dying before the second collective", flush=True)
        sys.stdout.flush()
        os._exit(0)

    # rank 0: enter a collective no peer will join
    t0 = time.monotonic()
    try:
        w = Tensor._from_value(jnp.asarray(np.ones((4,), np.float32)))
        dist.all_reduce(w)
        print("[rank 0] UNEXPECTED_COMPLETION", flush=True)
    except CommTimeoutError as e:
        dt = time.monotonic() - t0
        print(f"[rank 0] CAUGHT_TIMEOUT after {dt:.1f}s: {e}", flush=True)
    except Exception as e:
        dt = time.monotonic() - t0
        print(f"[rank 0] CAUGHT_ERROR after {dt:.1f}s: "
              f"{type(e).__name__}: {e}", flush=True)
    os._exit(0)  # comm thread may still be blocked; don't wait on it


if __name__ == "__main__":
    main()
