"""Comm watchdog tests (VERDICT r2 missing #5 / next-round #9).

Reference: paddle/phi/core/distributed/comm_task_manager.h:37 — background
timeout/error detection for collectives. The drill kills one rank between
two collectives and asserts the survivor RAISES within the timeout instead
of hanging (the round-2 behavior).
"""

import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "watchdog_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------------- unit level


@pytest.fixture(autouse=True)
def _fresh_watchdog():
    from paddle_tpu.distributed import watchdog

    watchdog.reset_poison()
    yield
    watchdog.reset_poison()


def test_watchdog_times_out_a_stuck_call():
    from paddle_tpu.distributed.watchdog import (
        CommTimeoutError,
        run_with_watchdog,
    )

    t0 = time.monotonic()
    with pytest.raises(CommTimeoutError):
        run_with_watchdog(lambda: time.sleep(60), timeout=1.0, desc="stuck")
    assert time.monotonic() - t0 < 10


def test_watchdog_poisons_subsequent_collectives():
    """After a timeout the blocked thread may later consume a peer's op —
    retrying would desync collective ordering, so the communicator refuses
    further work (NCCL comm-abort semantics)."""
    from paddle_tpu.distributed.watchdog import (
        CommTimeoutError,
        run_with_watchdog,
    )

    with pytest.raises(CommTimeoutError):
        run_with_watchdog(lambda: time.sleep(60), timeout=1.0, desc="first")
    with pytest.raises(CommTimeoutError, match="poisoned"):
        run_with_watchdog(lambda: 1, timeout=5.0, desc="second")


def test_watchdog_passes_results_and_errors_through():
    from paddle_tpu.distributed.watchdog import run_with_watchdog

    assert run_with_watchdog(lambda: 41 + 1, timeout=5.0) == 42

    class Boom(RuntimeError):
        pass

    def bad():
        raise Boom("inner")

    with pytest.raises(Boom):
        run_with_watchdog(bad, timeout=5.0)


def test_watchdog_disabled_runs_inline():
    from paddle_tpu.distributed.watchdog import run_with_watchdog

    assert run_with_watchdog(lambda: "x", timeout=0) == "x"


# ------------------------------------------------------------ process drill


@pytest.mark.slow
def test_dead_peer_raises_on_survivor():
    port = _free_port()
    env_base = dict(os.environ)
    env_base.pop("XLA_FLAGS", None)
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["PYTHONPATH"] = REPO + os.pathsep + env_base.get("PYTHONPATH", "")
    env_base["PADDLE_MASTER"] = f"127.0.0.1:{port}"
    env_base["PADDLE_TRAINERS_NUM"] = "2"

    procs = []
    for rank in range(2):
        env = dict(env_base)
        env["PADDLE_TRAINER_ID"] = str(rank)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    t0 = time.monotonic()
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            pytest.fail(f"worker hung (no watchdog): {out[-2000:]}")
        outs.append(out)
    wall = time.monotonic() - t0

    r0 = outs[0]
    assert "warmup ok" in r0, r0[-2000:]
    assert ("CAUGHT_TIMEOUT" in r0) or ("CAUGHT_ERROR" in r0), r0[-2000:]
    assert "UNEXPECTED_COMPLETION" not in r0
    # the survivor surfaced the failure well inside the drill budget
    assert wall < 150, f"took {wall:.0f}s"
