"""KV-cache incremental decoding (capability parity: decoder-serving ops —
masked_multihead_attention family; test pattern: cached decode must equal
full-context decode exactly)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (
    GPTForCausalLM,
    LlamaForCausalLM,
    gpt_tiny,
    llama_tiny,
)


def _tiny(name):
    if name == "gpt":
        return GPTForCausalLM(gpt_tiny(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_position_embeddings=64))
    return LlamaForCausalLM(llama_tiny(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        num_key_value_heads=2, max_position_embeddings=64))


@pytest.mark.parametrize("name", ["gpt", "llama"])
def test_cached_decode_matches_full_context(name, rng):
    paddle.seed(0)
    m = _tiny(name)
    m.eval()
    ids = paddle.to_tensor(rng.integers(0, 64, (2, 5)).astype(np.int32))
    out = m.generate(ids, max_new_tokens=6, temperature=0.0)
    full = ids
    for _ in range(6):
        logits = m(full)
        nxt = logits.numpy()[:, -1].argmax(-1).astype(np.int32)
        full = paddle.concat([full, paddle.to_tensor(nxt[:, None])], axis=1)
    np.testing.assert_array_equal(out.numpy(), full.numpy())


def test_generate_eos_stops(rng):
    paddle.seed(1)
    m = _tiny("gpt")
    m.eval()
    ids = paddle.to_tensor(rng.integers(0, 64, (1, 4)).astype(np.int32))
    # force eos: whatever the model emits first becomes the "eos"
    first = m.generate(ids, max_new_tokens=1, temperature=0.0)
    eos = int(first.numpy()[0, -1])
    out = m.generate(ids, max_new_tokens=8, temperature=0.0,
                     eos_token_id=eos)
    gen = out.numpy()[0, 4:]
    # after the first eos, everything is eos padding
    assert gen[0] == eos
    assert all(t == eos for t in gen[1:])


def test_generate_sampling_seeded(rng):
    paddle.seed(2)
    m = _tiny("llama")
    m.eval()
    ids = paddle.to_tensor(rng.integers(0, 64, (1, 4)).astype(np.int32))
    a = m.generate(ids, max_new_tokens=5, temperature=1.0, top_k=8, seed=7)
    b = m.generate(ids, max_new_tokens=5, temperature=1.0, top_k=8, seed=7)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    c = m.generate(ids, max_new_tokens=5, temperature=1.0, top_k=8, seed=8)
    assert a.shape == c.shape
