"""r5 dataset corpus closure (VERDICT r4 missing #3): the reference's
field contracts for Conll05st/Imikolov/Movielens/WMT14/WMT16 and
Flowers/VOC2012/DatasetFolder/ImageFolder, exercised against synthesized
fixtures in the reference archive formats (offline-friendly)."""

import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import WMT14, WMT16, Conll05st, Imikolov, Movielens
from paddle_tpu.vision.datasets import (
    DatasetFolder,
    Flowers,
    ImageFolder,
    VOC2012,
)


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


# ------------------------------------------------------------------- conll05
@pytest.fixture
def conll_files(tmp_path):
    words = b"The\ncat\nsat\n\n"
    # one predicate column: 'sat' is the verb, 'The cat' is A0
    props = b"-\t(A0*\n-\t*)\nsat\t(V*)\n\n"
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   gzip.compress(words))
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   gzip.compress(props))
    data = tmp_path / "conll.tgz"
    data.write_bytes(buf.getvalue())
    (tmp_path / "words.txt").write_text("The\ncat\nsat\n")
    (tmp_path / "verbs.txt").write_text("sat\n")
    (tmp_path / "targets.txt").write_text("A0\nV\nO\n")
    return data, tmp_path


def test_conll05st_contract(conll_files):
    data, d = conll_files
    ds = Conll05st(data_file=str(data),
                   word_dict_file=str(d / "words.txt"),
                   verb_dict_file=str(d / "verbs.txt"),
                   target_dict_file=str(d / "targets.txt"))
    assert len(ds) == 1
    item = ds[0]
    assert len(item) == 9  # reference conll05.py:278 9-tuple
    word_idx, n2, n1, c0, p1, p2, pred, mark, label = item
    assert word_idx.tolist() == [0, 1, 2]
    # verb at position 2: ctx_0 is 'sat'(2); n1='cat'(1); n2='The'(0)
    assert c0.tolist() == [2, 2, 2]
    assert n1.tolist() == [1, 1, 1]
    assert n2.tolist() == [0, 0, 0]
    assert mark.tolist() == [1, 1, 1]
    word_dict, verb_dict, label_dict = ds.get_dict()
    assert verb_dict == {"sat": 0}
    # labels: B-A0 I-A0 B-V expanded ids
    assert label.tolist() == [label_dict["B-A0"], label_dict["I-A0"],
                              label_dict["B-V"]]


# ------------------------------------------------------------------ imikolov
@pytest.fixture
def ptb_tar(tmp_path):
    train = b"a b a b a\nb a b a c\n" * 5
    valid = b"a b c\n" * 3
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt", train)
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt", valid)
    p = tmp_path / "simple-examples.tgz"
    p.write_bytes(buf.getvalue())
    return p


def test_imikolov_ngram_and_seq(ptb_tar):
    ds = Imikolov(data_file=str(ptb_tar), data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=1)
    assert len(ds) > 0
    item = ds[0]
    assert len(item) == 2 and all(x.shape == () for x in item)
    # every id within vocab
    vocab_n = len(ds.word_idx)
    flat = [int(x) for it in (ds[i] for i in range(len(ds))) for x in it]
    assert max(flat) < vocab_n
    assert "<unk>" in ds.word_idx and ds.word_idx["<unk>"] == vocab_n - 1

    seq = Imikolov(data_file=str(ptb_tar), data_type="SEQ", mode="test",
                   min_word_freq=1)
    src, trg = seq[0]
    # SEQ contract: src = <s>+ids, trg = ids+<e>, shifted by one
    assert src.shape == trg.shape
    assert src[0] == seq.word_idx["<s>"]
    assert trg[-1] == seq.word_idx["<e>"]
    np.testing.assert_array_equal(src[1:], trg[:-1])

    with pytest.raises(AssertionError):
        Imikolov(data_file=str(ptb_tar), data_type="NGRAM", window_size=-1)


# ----------------------------------------------------------------- movielens
@pytest.fixture
def ml_zip(tmp_path):
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Jumanji (1995)::Adventure\n").encode("latin-1")
    users = ("1::F::1::10::48067\n2::M::25::16::70072\n").encode("latin-1")
    ratings = ("1::1::5::978300760\n1::2::3::978302109\n"
               "2::1::4::978301968\n").encode("latin-1")
    p = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    return p


def test_movielens_contract(ml_zip):
    ds = Movielens(data_file=str(ml_zip), mode="train", test_ratio=0.0)
    assert len(ds) == 3
    item = ds[0]
    # usr(4) + movie(3) + rating(1) = 8 arrays
    assert len(item) == 8
    uid, gender, age, job, mid, cats, title, rating = item
    assert uid.tolist() == [1]
    assert gender.tolist() == [1]  # F -> 1
    assert age.tolist() == [0]     # age 1 -> bucket 0
    assert job.tolist() == [10]
    assert mid.tolist() == [1]
    assert len(cats) == 2          # Animation|Comedy
    assert len(title) == 2         # "Toy Story"
    assert rating.tolist() == [5.0 * 2 - 5.0]
    # test split empty at ratio 0
    assert len(Movielens(data_file=str(ml_zip), mode="test",
                         test_ratio=0.0)) == 0


# ---------------------------------------------------------------- wmt14 / 16
@pytest.fixture
def wmt14_tar(tmp_path):
    dict_txt = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    pairs = b"hello world\tbonjour monde\nhello\tbonjour\n"
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", dict_txt)
        _add_bytes(tf, "wmt14/trg.dict",
                   b"<s>\n<e>\n<unk>\nbonjour\nmonde\n")
        _add_bytes(tf, "wmt14/train/train", pairs)
        _add_bytes(tf, "wmt14/test/test", pairs[:25])
    p = tmp_path / "wmt14.tgz"
    p.write_bytes(buf.getvalue())
    return p


def test_wmt14_contract(wmt14_tar):
    ds = WMT14(data_file=str(wmt14_tar), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert src.tolist() == [0, 3, 4, 1]       # <s> hello world <e>
    assert trg.tolist() == [0, 3, 4]          # <s> bonjour monde
    assert trg_next.tolist() == [3, 4, 1]     # bonjour monde <e>
    d_src, d_trg = ds.get_dict()
    assert d_src["hello"] == 3
    r_src, _ = ds.get_dict(reverse=True)
    assert r_src[3] == "hello"
    with pytest.raises(AssertionError):
        WMT14(data_file=str(wmt14_tar), mode="train", dict_size=-1)


@pytest.fixture
def wmt16_tar(tmp_path):
    # wmt16/{train,test,val}: "en\tde" columns (reference wmt16.py src_col)
    train = b"hello world\thallo welt\nhello\thallo\n" * 3
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        _add_bytes(tf, "wmt16/train", train)
        _add_bytes(tf, "wmt16/test", train[:22])
        _add_bytes(tf, "wmt16/val", train[:22])
    p = tmp_path / "wmt16.tar.gz"
    p.write_bytes(buf.getvalue())
    return p


def test_wmt16_contract(wmt16_tar):
    ds = WMT16(data_file=str(wmt16_tar), mode="train", src_dict_size=10,
               trg_dict_size=10, lang="en")
    assert len(ds) == 6
    src, trg, trg_next = ds[0]
    sd = ds.get_dict("en")
    td = ds.get_dict("de")
    assert src[0] == sd["<s>"] and src[-1] == sd["<e>"]
    assert trg[0] == sd["<s>"]
    np.testing.assert_array_equal(trg[1:], trg_next[:-1])
    assert sd["hello"] >= 3 and td["hallo"] >= 3  # after reserved marks
    # lang='de' swaps source/target columns
    ds_de = WMT16(data_file=str(wmt16_tar), mode="val", src_dict_size=10,
                  trg_dict_size=10, lang="de")
    s2, _, _ = ds_de[0]
    assert len(ds_de) == 1
    rev = ds_de.get_dict("de", reverse=True)
    assert rev[int(s2[1])] == "hallo"


# ------------------------------------------------------------ vision corpus
def _png_bytes(w=4, h=4, color=(255, 0, 0)):
    from PIL import Image

    img = Image.new("RGB", (w, h), color)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def _jpg_bytes(w=4, h=4, color=(0, 255, 0)):
    from PIL import Image

    img = Image.new("RGB", (w, h), color)
    buf = io.BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def test_dataset_folder_and_image_folder(tmp_path):
    for cls in ("cat", "dog"):
        d = tmp_path / "root" / cls
        os.makedirs(d)
        for i in range(2):
            (d / f"{i}.png").write_bytes(_png_bytes())
        (d / "notes.txt").write_text("skip me")
    ds = DatasetFolder(str(tmp_path / "root"))
    assert ds.classes == ["cat", "dog"]
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 4 and ds.targets == [0, 0, 1, 1]
    img, target = ds[0]
    assert target == 0 and img.size == (4, 4)

    flat = ImageFolder(str(tmp_path / "root"))
    assert len(flat.samples) == 4
    item = flat[0]
    assert isinstance(item, list) and len(item) == 1

    with pytest.raises(RuntimeError):
        DatasetFolder(str(tmp_path / "root"), extensions=(".xyz",))


def test_flowers_contract(tmp_path):
    import scipy.io as sio

    n = 6
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for i in range(1, n + 1):
            _add_bytes(tf, "jpg/image_%05d.jpg" % i, _jpg_bytes())
    (tmp_path / "102flowers.tgz").write_bytes(buf.getvalue())
    sio.savemat(tmp_path / "imagelabels.mat",
                {"labels": np.arange(1, n + 1)[None, :]})
    sio.savemat(tmp_path / "setid.mat",
                {"tstid": np.array([[1, 2, 3, 4]]),
                 "trnid": np.array([[5, 6]]),
                 "valid": np.array([[5]])})
    ds = Flowers(data_file=str(tmp_path / "102flowers.tgz"),
                 label_file=str(tmp_path / "imagelabels.mat"),
                 setid_file=str(tmp_path / "setid.mat"), mode="train")
    assert len(ds) == 4  # tstid flags TRAIN (reference quirk)
    img, label = ds[0]
    assert label.tolist() == [1] and img.size == (4, 4)
    test = Flowers(data_file=str(tmp_path / "102flowers.tgz"),
                   label_file=str(tmp_path / "imagelabels.mat"),
                   setid_file=str(tmp_path / "setid.mat"), mode="test",
                   backend="cv2")
    assert len(test) == 2
    arr, label = test[0]
    assert isinstance(arr, np.ndarray) and label.tolist() == [5]


def test_voc2012_contract(tmp_path):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt",
                   b"img1\nimg2\n")
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
                   b"img1\n")
        for name in ("img1", "img2"):
            _add_bytes(tf, f"VOCdevkit/VOC2012/JPEGImages/{name}.jpg",
                       _jpg_bytes())
            _add_bytes(tf,
                       f"VOCdevkit/VOC2012/SegmentationClass/{name}.png",
                       _png_bytes())
    p = tmp_path / "voc.tar"
    p.write_bytes(buf.getvalue())
    ds = VOC2012(data_file=str(p), mode="train")
    assert len(ds) == 2
    img, mask = ds[0]
    assert img.size == (4, 4) and mask.size == (4, 4)
    cv = VOC2012(data_file=str(p), mode="valid", backend="cv2")
    assert len(cv) == 1
    arr, m = cv[0]
    assert isinstance(arr, np.ndarray) and arr.dtype == np.float32
