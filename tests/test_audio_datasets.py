"""r5 audio dataset corpus (reference python/paddle/audio/datasets/):
AudioClassificationDataset feat routing, ESC50 CSV folds, TESS
filename-parsed labels — fixtures written through the framework's own
wave backend."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.audio import backends
from paddle_tpu.audio.datasets import ESC50, TESS, AudioClassificationDataset


def _write_wav(path, freq=440.0, sr=16000, n=800):
    t = np.arange(n) / sr
    wav = (0.5 * np.sin(2 * np.pi * freq * t)).astype(np.float32)
    backends.save(str(path), paddle.to_tensor(wav[None, :]), sr)


@pytest.fixture
def esc50_tree(tmp_path):
    audio = tmp_path / "ESC-50-master" / "audio"
    meta = tmp_path / "ESC-50-master" / "meta"
    os.makedirs(audio)
    os.makedirs(meta)
    rows = ["filename,fold,target,category,esc10,src_file,take"]
    for i in range(10):
        name = f"1-{i}-A-{i % 5}.wav"
        _write_wav(audio / name, freq=200 + 40 * i)
        rows.append(f"{name},{i % 5 + 1},{i % 5},cat{i % 5},False,{i},A")
    (meta / "esc50.csv").write_text("\n".join(rows) + "\n")
    return tmp_path


def test_esc50_folds_and_items(esc50_tree):
    train = ESC50(mode="train", split=1, data_dir=str(esc50_tree))
    dev = ESC50(mode="dev", split=1, data_dir=str(esc50_tree))
    assert len(train) == 8 and len(dev) == 2  # fold1 = 2 of 10
    wav, label = train[0]
    assert wav.shape[-1] == 800 and 0 <= int(label) < 5
    # no overlap between splits
    assert not (set(train.files) & set(dev.files))


def test_esc50_feature_routing(esc50_tree):
    ds = ESC50(mode="dev", split=1, data_dir=str(esc50_tree),
               feat_type="mfcc", n_mfcc=13, n_fft=256)
    feat, label = ds[0]
    assert feat.shape[0] == 13  # [n_mfcc, frames]
    ds2 = ESC50(mode="dev", split=1, data_dir=str(esc50_tree),
                feat_type="logmelspectrogram", n_fft=256, n_mels=32)
    feat2, _ = ds2[0]
    assert feat2.shape[0] == 32
    with pytest.raises(RuntimeError):
        AudioClassificationDataset([], [], feat_type="bogus")


@pytest.fixture
def tess_tree(tmp_path):
    root = tmp_path / "TESS_Toronto_emotional_speech_set"
    emotions = ["angry", "happy", "sad", "neutral", "fear"]
    os.makedirs(root)
    for i in range(10):
        emo = emotions[i % len(emotions)]
        _write_wav(root / f"OAF_word{i}_{emo}.wav", freq=150 + 25 * i)
    return tmp_path


def test_tess_labels_and_folds(tess_tree):
    train = TESS(mode="train", n_folds=5, split=1, data_dir=str(tess_tree))
    dev = TESS(mode="dev", n_folds=5, split=1, data_dir=str(tess_tree))
    assert len(train) == 8 and len(dev) == 2
    labels = sorted({int(l) for _, l in
                     ((train[i]) for i in range(len(train)))})
    assert all(0 <= l < len(TESS.label_list) for l in labels)
    wav, _ = train[0]
    assert wav.shape[-1] == 800
    with pytest.raises(AssertionError):
        TESS(n_folds=0, data_dir=str(tess_tree))


def test_missing_tree_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ESC50(data_dir=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        TESS(data_dir=str(tmp_path))
