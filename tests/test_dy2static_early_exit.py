"""Early-exit dy2static (VERDICT r4 missing #4): return/break/continue in
tensor-dependent control flow, shaped after the reference's transformer
tests (jit/dy2static/transformers/return_transformer.py,
break_continue_transformer.py). Every case asserts the transformed function
equals its eager (python) semantics on BOTH sides of the predicate."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.jit import to_static


def t(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


def run_both(fn, *args):
    """eager result vs to_static result."""
    eager = fn(*args)
    st = to_static(fn)
    traced = st(*args)
    return eager, traced


def check(fn, *args):
    eager, traced = run_both(fn, *args)
    np.testing.assert_allclose(
        np.asarray(traced.numpy()), np.asarray(eager.numpy()), rtol=1e-5,
        err_msg=f"{fn.__name__}{args}")


def test_return_in_one_branch():
    def f(x):
        if paddle.sum(x) > 0:
            return x * 2.0
        y = x + 1.0
        return y * 3.0

    check(f, t([1.0, 2.0]))
    check(f, t([-1.0, -2.0]))


def test_return_in_nested_if():
    def f(x):
        if paddle.sum(x) > 0:
            if paddle.max(x) > 5.0:
                return x * 10.0
            return x * 2.0
        return -x

    check(f, t([6.0, 1.0]))
    check(f, t([1.0, 1.0]))
    check(f, t([-1.0, -1.0]))


def test_return_inside_tensor_while():
    def f(x):
        i = paddle.to_tensor(0.0)
        while i < 10.0:
            x = x + 1.0
            if paddle.sum(x) > 6.0:
                return x * 100.0
            i = i + 1.0
        return x

    check(f, t([0.0, 0.0]))   # early return fires at some iteration
    check(f, t([-100.0, 0.0]))  # runs to loop end


def test_return_inside_range_for_tensor_bound():
    def f(x, n):
        acc = paddle.to_tensor(0.0)
        for i in range(n):
            acc = acc + paddle.sum(x)
            if acc > 4.0:
                return acc * 10.0
        return acc

    check(f, t([1.0]), paddle.to_tensor(np.int32(10)))
    check(f, t([0.1]), paddle.to_tensor(np.int32(3)))


def test_statements_after_returning_if_are_guarded():
    def f(x):
        y = x * 1.0
        if paddle.sum(x) > 0:
            return y + 100.0
        y = y + 1.0   # must NOT run when the branch returned
        return y

    check(f, t([1.0]))
    check(f, t([-1.0]))


def test_break_continue_still_work_with_return_rewrite():
    def f(x):
        total = paddle.to_tensor(0.0)
        i = paddle.to_tensor(0.0)
        while i < 8.0:
            i = i + 1.0
            if paddle.sum(x) * i > 1000.0:
                break
            if i > 4.0:
                continue
            total = total + i
        if total > 100.0:
            return -total
        return total + paddle.sum(x)

    check(f, t([1.0]))
    check(f, t([500.0]))


def test_both_branches_return_still_works():
    def f(x):
        if paddle.sum(x) > 0:
            return x * 2.0
        else:
            return x * -3.0

    check(f, t([2.0]))
    check(f, t([-2.0]))


def test_plain_python_early_return_untouched():
    # python predicate: exact python semantics (no tracing involved)
    def f(x, flag):
        if flag:
            return x * 2.0
        for _ in range(3):
            x = x + 1.0
        return x

    check(f, t([1.0]), True)
    check(f, t([1.0]), False)


def test_return_none_fall_off():
    def f(x):
        if paddle.sum(x) > 0:
            x = x + 1.0
        return x

    check(f, t([1.0]))
    check(f, t([-1.0]))


def test_return_inside_nested_while():
    """The flag must break BOTH loop levels (the rewriter appends an
    if-flag-break per enclosing loop)."""
    def f(x):
        i = paddle.to_tensor(0.0)
        while i < 4.0:
            j = paddle.to_tensor(0.0)
            while j < 4.0:
                x = x + 1.0
                if paddle.sum(x) > 5.0:
                    return x * 100.0
                j = j + 1.0
            i = i + 1.0
        return x

    check(f, t([0.0]))      # returns mid-inner-loop
    check(f, t([-100.0]))   # runs both loops to completion


def test_return_inside_for_over_tensor():
    """for over a TENSOR iterates rows (graph break per row); an early
    return inside must still capture the right value."""
    def f(m):
        acc = paddle.to_tensor(0.0)
        for row in m:
            acc = acc + paddle.sum(row)
            if acc > 2.5:
                return acc * 10.0
        return acc

    check(f, t([[1.0, 1.0], [1.0, 1.0], [5.0, 5.0]]))  # early at row 2
    check(f, t([[0.1, 0.1], [0.1, 0.1]]))              # completes
