"""Parity audit: registry coverage against the reference op manifest.

The reference's op surface is pinned in paddle_tpu/ops/ref_manifest.py
(extracted from /root/reference/paddle/phi/ops/yaml/{ops,fused_ops,sparse_ops}
.yaml — 538 unique ops). VERDICT r1 required an enforced audit with a
justified skip list and >=90% coverage of the remainder.
"""

import paddle_tpu  # noqa: F401  (triggers all registrations)
from paddle_tpu.ops.parity import SKIPPED_OPS
from paddle_tpu.ops.ref_manifest import REFERENCE_OPS
from paddle_tpu.ops.registry import all_ops

REQUIRED_COVERAGE = 0.90


def test_skip_list_is_valid():
    # every skip names a real reference op and carries a reason
    for name, reason in SKIPPED_OPS.items():
        assert name in REFERENCE_OPS, f"skip of unknown op {name}"
        assert isinstance(reason, str) and len(reason) > 10, name
    # skips must stay a small, auditable fraction (<15% of the manifest)
    assert len(SKIPPED_OPS) < 0.15 * len(REFERENCE_OPS)


def test_reference_coverage():
    registered = set(all_ops().keys())
    required = [n for n in REFERENCE_OPS if n not in SKIPPED_OPS]
    missing = sorted(n for n in required if n not in registered)
    cov = 1 - len(missing) / len(required)
    assert cov >= REQUIRED_COVERAGE, (
        f"op coverage {cov:.1%} < {REQUIRED_COVERAGE:.0%}; "
        f"{len(missing)} missing: {missing[:40]}..."
    )


def test_report_counts(capsys):
    registered = set(all_ops().keys())
    required = [n for n in REFERENCE_OPS if n not in SKIPPED_OPS]
    present = [n for n in required if n in registered]
    print(f"manifest={len(REFERENCE_OPS)} skipped={len(SKIPPED_OPS)} "
          f"required={len(required)} present={len(present)}")
