"""Async zero-bubble serving engine (scheduler dispatch_depth > 0).

Identity oracle: at every ``dispatch_depth`` the engine must produce
token streams bit-identical to the synchronous (depth-0) engine and to
the per-request eager decode — dispatch-ahead only moves WHEN the host
observes a step's tokens, never which tokens the step computes. Pinned
here under plain load, forced preemption, prefix-cache eviction
pressure, mid-flight cancel/deadline, and injected transient faults.
Plus: the one-compiled-decode-program / zero-steady-state-recompile
invariant at depth > 0, the engine block in ``debug_state()`` and the
flight ring, shutdown's drain-everything contract, and serve_bench's
quiesce-on-death partial artifact.
"""

import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.resilience import FaultPlan, fault_plan, get_injector
from paddle_tpu.serving import ContinuousBatchingScheduler, SchedulerConfig

DEPTHS = (0, 1, 2)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts these decode programs' NUMERICS (wrong
    generated tokens) even when the persistent cache was written by the
    SAME jax build in the same session — serving tests compile fresh (see
    test_serving_sched.py for the full history)."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _eager_oracle(model, prompt, max_new):
    out = model.generate(paddle.to_tensor(prompt[None, :].astype(np.int64)),
                         max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out.numpy())[0]


def _sched(model, depth, **over):
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=8,
              dispatch_depth=depth)
    kw.update(over)
    return ContinuousBatchingScheduler(model, SchedulerConfig(**kw))


def _drain(sched, guard=3000):
    while sched.has_unfinished():
        sched.step()
        guard -= 1
        assert guard > 0, "scheduler did not drain"
    return dict(sched._finished)


def _pool_clean(sched):
    if sched.prefix_cache is not None:
        sched.prefix_cache.flush()
    assert sched.allocator.num_used_blocks == 0, (
        f"block leak: {sched.allocator.num_used_blocks} still held")


# ------------------------------------------------------- identity oracle

def test_depths_match_eager_ragged(model):
    """6 ragged requests through 3 slots at every depth == per-request
    eager greedy, token for token."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1000, int(n))
               for n in rng.integers(4, 14, 6)]
    refs = [_eager_oracle(model, p, 5) for p in prompts]
    for d in DEPTHS:
        sched = _sched(model, d, max_num_seqs=3)
        outs = sched.generate(prompts, max_new_tokens=5)
        for p, o, ref in zip(prompts, outs, refs):
            np.testing.assert_array_equal(o, ref)
        sched.shutdown()
        _pool_clean(sched)


def test_depths_identical_under_forced_preemption(model):
    """Pool sized so both sequences admit but cannot both finish: the
    preempt/resume cycle must commute with dispatch-ahead (the drain
    barrier before preemption makes the resume see committed state)."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1000, 10), rng.integers(0, 1000, 9)]
    ref = None
    for d in DEPTHS:
        sched = _sched(model, d, block_size=4, num_blocks=6)
        outs = sched.generate(prompts, max_new_tokens=8)
        assert sched.metrics.snapshot()["preemptions"] >= 1
        if ref is None:
            ref = outs
        else:
            for a, b in zip(ref, outs):
                np.testing.assert_array_equal(a, b)
        sched.shutdown()
        _pool_clean(sched)


def test_depths_identical_under_prefix_cache_eviction(model):
    """Prefix cache on with a pool far below the retired-KV footprint:
    continuous LRU eviction while steps are in flight must not change a
    single token vs the synchronous engine."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 1000, int(n))
               for n in rng.integers(9, 20, 8)]
    ref = None
    for d in DEPTHS:
        sched = _sched(model, d, enable_prefix_caching=True, num_blocks=8)
        outs = sched.generate(prompts, max_new_tokens=5)
        assert sched.prefix_cache_stats()["evicted_blocks"] > 0
        if ref is None:
            ref = outs
        else:
            for a, b in zip(ref, outs):
                np.testing.assert_array_equal(a, b)
        sched.shutdown()
        _pool_clean(sched)


# ------------------------------------------------- mid-flight lifecycle

def test_cancel_mid_flight_exact_parity(model):
    """A cancel between step() calls must land on exactly the state the
    synchronous engine would have: the in-flight pipeline drains first,
    so the cancelled request's tokens-so-far AND every survivor's full
    stream are depth-invariant."""
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, 1000, 8), rng.integers(0, 1000, 6),
               rng.integers(0, 1000, 7)]
    results = {}
    for d in DEPTHS:
        sched = _sched(model, d)
        rids = [sched.add_request(p, max_new_tokens=10) for p in prompts]
        for _ in range(3):
            sched.step()
        cancelled = sched.cancel(rids[0])
        assert cancelled.finish_reason == "cancelled"
        outs = _drain(sched)
        sched.shutdown()
        _pool_clean(sched)
        results[d] = (list(cancelled.generated_ids),
                      {r: list(outs[r].token_ids) for r in rids[1:]})
    assert results[1] == results[0]
    assert results[2] == results[0]


def test_deadline_mid_flight(model):
    rng = np.random.default_rng(5)
    sched = _sched(model, 2, max_num_seqs=1)
    rid = sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=50,
                            deadline_s=1e-6)
    outs = _drain(sched)
    assert outs[rid].finish_reason == "deadline"
    sched.shutdown()
    _pool_clean(sched)


def test_transient_faults_at_depth_token_identical(model):
    """Injected decode-step faults with two steps in flight: the retry
    path drains the pipeline, replays, and every surviving stream stays
    bit-identical to the fault-free synchronous run."""
    rng = np.random.default_rng(6)
    prompts = [rng.integers(0, 1000, int(n))
               for n in rng.integers(4, 10, 4)]
    base_sched = _sched(model, 0)
    base_rids = [base_sched.add_request(p, max_new_tokens=5)
                 for p in prompts]
    base = _drain(base_sched)
    base_sched.shutdown()

    sched = _sched(model, 2)
    rids = [sched.add_request(p, max_new_tokens=5) for p in prompts]
    with fault_plan(FaultPlan(seed=0).on("serving.decode_step",
                                         at=(2, 5))):
        outs = _drain(sched)
        assert get_injector().snapshot()["fires"].get(
            "serving.decode_step", 0) >= 1
    for r0, r1 in zip(base_rids, rids):
        assert outs[r1].finish_reason in ("length", "eos")
        np.testing.assert_array_equal(base[r0].token_ids,
                                      outs[r1].token_ids)
    sched.shutdown()
    _pool_clean(sched)


# ----------------------------------------- invariants + introspection

def test_zero_steady_state_recompiles_at_depth(model):
    """The tentpole invariant: dispatch-ahead must reuse the ONE compiled
    decode program — a second workload after mark_steady() compiles
    nothing at any depth."""
    rng = np.random.default_rng(7)
    for d in (1, 2):
        sched = _sched(model, d, max_num_seqs=3)
        sched.generate([rng.integers(0, 1000, int(n))
                        for n in rng.integers(4, 14, 5)], max_new_tokens=4)
        stats = sched.compile_stats()
        assert stats["compiles"] == sched.num_programs() == 2
        sched.mark_steady()
        sched.generate([rng.integers(0, 1000, int(n))
                        for n in rng.integers(4, 14, 6)], max_new_tokens=4)
        stats = sched.compile_stats()
        assert stats["steady_state_recompiles"] == 0
        assert stats["compiles"] == 2
        sched.shutdown()


def test_debug_state_and_flight_expose_engine(model):
    rng = np.random.default_rng(8)
    sched = _sched(model, 2)
    sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=8)
    for _ in range(3):
        sched.step()
    dbg = sched.debug_state()
    assert dbg["engine"]["dispatch_depth"] == 2
    assert 0 <= dbg["engine"]["in_flight_steps"] <= 2
    assert dbg["engine"]["drain_wait_seconds"] >= 0
    _drain(sched)
    # decode-step rows in the flight ring carry the engine fields at
    # depth > 0 (and ONLY then — depth-0 dumps stay byte-stable)
    rows = [r for r in sched.flight.dump() if "dispatch_depth" in r]
    assert rows and all(r["dispatch_depth"] == 2 for r in rows)
    assert all("in_flight_steps" in r for r in rows)
    sched.shutdown()

    sync = _sched(model, 0)
    sync.add_request(rng.integers(0, 1000, 6), max_new_tokens=4)
    _drain(sync)
    assert sync.debug_state()["engine"]["in_flight_steps"] == 0
    assert all("dispatch_depth" not in r for r in sync.flight.dump())


def test_shutdown_drains_in_flight_and_frees(model):
    rng = np.random.default_rng(9)
    sched = _sched(model, 2)
    for _ in range(3):
        sched.add_request(rng.integers(0, 1000, 8), max_new_tokens=20)
    for _ in range(4):
        sched.step()
    counts = sched.shutdown()
    assert counts["drained_in_flight"] >= 1, "pipeline should be in flight"
    assert counts["cancelled"] >= 1
    assert not sched.has_unfinished()
    _pool_clean(sched)
    # idempotent: nothing left to drain or cancel
    again = sched.shutdown()
    assert again == {"drained_in_flight": 0, "cancelled": 0}


# --------------------------------------------- serve_bench death drain

def test_serve_bench_quiesces_live_engines_on_death(tmp_path, monkeypatch):
    """A bench dying with dispatched-but-unobserved steps in flight must
    drain and release them BEFORE the partial artifact is written, and
    the artifact must record that nothing leaked."""
    import tools.serve_bench as sb

    paddle.seed(7)
    model = GPTForCausalLM(gpt_tiny(num_layers=1))

    def boom(**kw):
        sched = sb._track(ContinuousBatchingScheduler(
            model, SchedulerConfig(max_num_seqs=2, max_seq_len=64,
                                   block_size=8, dispatch_depth=2)))
        rng = np.random.default_rng(0)
        for _ in range(2):
            sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=30)
        for _ in range(4):
            sched.step()
        assert len(sched._inflight) >= 1
        raise RuntimeError("mid-bench death with steps in flight")

    sb._LIVE_SCHEDS.clear()
    monkeypatch.setattr(sb, "run_load", boom)
    out = tmp_path / "BENCH_dead.json"
    with pytest.raises(RuntimeError, match="mid-bench death"):
        sb.main(["--smoke", "--out", str(out)])
    art = json.loads(out.read_text())
    assert art["completed"] is False
    entries = art["quiesced_schedulers"]
    assert len(entries) == 1
    q = entries[0]
    assert q["error"] is None
    assert q["drained_in_flight"] >= 1
    assert q["cancelled"] == 2
    assert q["blocks_leaked"] == 0
