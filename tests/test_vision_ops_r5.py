"""r5 vision.ops closure (reference vision/ops.py:753 deform_conv2d, :960
DeformConv2D, :1156 distribute_fpn_proposals, :1301 read_file, :1344
decode_jpeg, :1810 ConvNormActivation + RoI class wrappers). The deform
oracles are analytic: zero offsets == standard conv; integer offsets ==
conv over the shifted image; the v2 mask is linear."""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def t(x):
    return paddle.to_tensor(np.asarray(x))


def _conv_ref(x, w, stride=1, padding=0):
    return np.asarray(jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(padding, padding)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW")))


def test_deform_conv_zero_offset_is_conv():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 4, 8, 8)).astype(np.float32)
    w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32) * 0.2
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out = np.asarray(V.deform_conv2d(t(x), t(off), t(w)).numpy())
    np.testing.assert_allclose(out, _conv_ref(x, w), rtol=1e-4, atol=1e-5)


def test_deform_conv_integer_offset_shifts():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 2, 10, 10)).astype(np.float32)
    w = rng.standard_normal((3, 2, 3, 3)).astype(np.float32) * 0.2
    # constant offset (dy=1, dx=0) on every tap == conv over x shifted up
    off = np.zeros((1, 18, 8, 8), np.float32)
    off[:, 0::2] = 1.0  # y components
    out = np.asarray(V.deform_conv2d(t(x), t(off), t(w)).numpy())
    x_shift = np.zeros_like(x)
    x_shift[:, :, :-1] = x[:, :, 1:]
    ref = _conv_ref(x_shift, w)
    # interior matches exactly (border rows touch the zero pad)
    np.testing.assert_allclose(out[:, :, :-1], ref[:, :, :-1],
                               rtol=1e-4, atol=1e-5)


def test_deform_conv_v2_mask_linear_and_grads():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    w = rng.standard_normal((2, 2, 3, 3)).astype(np.float32) * 0.3
    off = rng.standard_normal((1, 18, 4, 4)).astype(np.float32) * 0.3
    mask = np.full((1, 9, 4, 4), 0.5, np.float32)
    full = np.asarray(V.deform_conv2d(
        t(x), t(off), t(w), mask=t(np.ones_like(mask))).numpy())
    half = np.asarray(V.deform_conv2d(t(x), t(off), t(w),
                                      mask=t(mask)).numpy())
    np.testing.assert_allclose(half, 0.5 * full, rtol=1e-4, atol=1e-6)
    # grads flow to offsets (the point of deformable conv)
    xo, oo, wo = t(x), t(off), t(w)
    for v in (xo, oo, wo):
        v.stop_gradient = False
    loss = paddle.sum(V.deform_conv2d(xo, oo, wo) ** 2)
    loss.backward()
    assert np.isfinite(np.asarray(oo.grad.numpy())).all()
    assert float(np.abs(np.asarray(oo.grad.numpy())).max()) > 0


def test_deform_conv_layer_and_groups():
    paddle.seed(0)
    layer = V.DeformConv2D(4, 6, 3, padding=1, groups=2,
                           deformable_groups=2)
    x = t(np.random.default_rng(3).standard_normal(
        (1, 4, 6, 6)).astype(np.float32))
    off = t(np.zeros((1, 2 * 2 * 9, 6, 6), np.float32))
    out = layer(x, off)
    assert tuple(out.shape) == (1, 6, 6, 6)


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10],       # small -> low level
                     [0, 0, 224, 224],     # refer scale -> refer level
                     [0, 0, 500, 500]],    # large -> high level
                    np.float32)
    multi, restore, nums = V.distribute_fpn_proposals(
        t(rois), 2, 5, 4, 224, rois_num=t(np.array([3], np.int32)))
    assert len(multi) == 4
    sizes = [m.shape[0] for m in multi]
    assert sum(sizes) == 3
    assert multi[0].shape[0] == 1          # the small roi at level 2
    assert multi[2].shape[0] == 1          # 224 -> level 4
    r = np.asarray(restore.numpy()).ravel()
    cat = np.concatenate([np.asarray(m.numpy()) for m in multi if m.shape[0]])
    np.testing.assert_allclose(cat[r], rois)
    assert nums is not None


def test_read_file_decode_jpeg(tmp_path):
    from PIL import Image

    img = np.zeros((8, 8, 3), np.uint8)
    img[..., 0] = 200
    p = str(tmp_path / "t.jpg")
    Image.fromarray(img).save(p, quality=95)
    data = V.read_file(p)
    assert data.dtype == paddle.uint8 or "uint8" in str(data.dtype)
    chw = V.decode_jpeg(data)
    assert tuple(chw.shape) == (3, 8, 8)
    arr = np.asarray(chw.numpy())
    assert arr[0].mean() > 150 and arr[1].mean() < 60
    gray = V.decode_jpeg(data, mode="gray")
    assert tuple(gray.shape) == (1, 8, 8)


def test_conv_norm_activation_and_roi_wrappers():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    block = V.ConvNormActivation(3, 8, 3, stride=2)
    x = t(np.random.default_rng(4).standard_normal(
        (2, 3, 8, 8)).astype(np.float32))
    out = block(x)
    assert tuple(out.shape) == (2, 8, 4, 4)
    assert float(np.asarray(out.numpy()).min()) >= 0  # ReLU applied

    feat = t(np.random.default_rng(5).standard_normal(
        (1, 4, 16, 16)).astype(np.float32))
    boxes = t(np.array([[0, 0, 8, 8], [4, 4, 12, 12]], np.float32))
    bn = t(np.array([2], np.int32))
    ra = V.RoIAlign(output_size=4)(feat, boxes, bn)
    assert tuple(ra.shape) == (2, 4, 4, 4)
    rp = V.RoIPool(output_size=4)(feat, boxes, bn)
    assert tuple(rp.shape) == (2, 4, 4, 4)


def test_conv_norm_activation_no_norm_bias():
    block = V.ConvNormActivation(3, 4, 3, norm_layer=None,
                                 activation_layer=None)
    x = t(np.zeros((1, 3, 6, 6), np.float32))
    out = block(x)
    assert tuple(out.shape) == (1, 4, 6, 6)


def test_ops_class_identity():
    m = V.DeformConv2D(2, 2, 3)
    assert isinstance(m, V.DeformConv2D)
    b = V.ConvNormActivation(2, 2)
    assert isinstance(b, V.ConvNormActivation)
