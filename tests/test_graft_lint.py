"""graft_lint: the framework-invariant static-analysis suite as tier-1.

Three layers of pinning:

1. Fixture tests per rule — one known-bad and one known-clean snippet per
   checker, run through the real driver machinery (no jax devices needed:
   the suite is stdlib-ast only).
2. Suppression + baseline round trips — ``# graft-lint: disable=...`` in
   its three forms, and the accepted-findings baseline absorbing exactly
   the findings it records (a NEW finding still fails).
3. The acceptance bar, both directions: ``python tools/lint.py`` over the
   real repo exits 0 with zero non-baselined findings, and seeding a
   known-bad construct makes it exit non-zero with a correct file:line.

Plus regression tests for the real bugs the first full-repo run surfaced
(unguarded registry/histogram/flight-recorder state shared with the
ObservabilityEndpoint scrape thread).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graft_lint import Baseline, run_lint  # noqa: E402
from tools.graft_lint.core import Module  # noqa: E402


def _lint(tmp_path, rules=None, baseline=None):
    """Run the suite over the tmp fixture tree; returns (report, findings
    as dicts)."""
    report = run_lint(str(tmp_path), [str(tmp_path)], rules=rules,
                      baseline_path=baseline
                      or str(tmp_path / "no_baseline.json"))
    report.pop("_finding_objs")
    return report


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return p


def _rules_hit(report, rule):
    return [f for f in report["findings"] if f["rule"] == rule
            and not f["suppressed"] and not f["baselined"]]


# ---------------------------------------------------------------- fixtures

def test_tracing_hazard_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import jax.numpy as jnp
        import numpy as np

        def to_static(fn):
            return fn

        def helper(x):
            return x.item() + 1          # hazard, reachable via traced()

        @to_static
        def traced(x):
            if bool(x):                   # hazard: bool() on traced value
                return helper(x)
            return jnp.sum(x)             # clean: stays in jnp

        def eager_only(x):
            return np.asarray(x).item()   # NOT reachable from a trace root
    """)
    report = _lint(tmp_path, rules=["tracing-hazard"])
    hits = _rules_hit(report, "tracing-hazard")
    symbols = {f["symbol"] for f in hits}
    assert "helper" in symbols            # call-graph reachability
    assert "traced" in symbols            # direct hazard in the root
    assert "eager_only" not in symbols    # eager code is out of scope
    assert all(f["file"] == "mod.py" and f["line"] > 0 for f in hits)


def test_recompile_hazard_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np

        def _bucket(n, lo=16):
            b = lo
            while b < n:
                b *= 2
            return b

        class Sched:
            def bad(self, ids):
                P = len(ids)
                a = np.zeros((1, P), np.int32)      # raw data-dep width
                return self._step_fn(a)

            def good(self, ids):
                Pb = min(_bucket(len(ids)), 512)
                a = np.zeros((1, Pb), np.int32)     # bucketed: clean
                return self._step_fn(a)

            def no_jit_here(self, ids):
                return np.zeros((len(ids),))        # no jit callsite: clean
    """)
    report = _lint(tmp_path, rules=["recompile-hazard"])
    hits = _rules_hit(report, "recompile-hazard")
    assert [f["symbol"] for f in hits] == ["Sched.bad"]


def test_host_sync_in_hot_loop_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np

        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Loop:
            @hot_path
            def decode(self, t):
                bad = np.asarray(t.numpy())          # unmetered sync
                with self.stall.timed("sampling_sync"):
                    ok = np.asarray(t.numpy())       # metered: allowed
                return bad, ok

            def not_hot(self, t):
                return t.numpy()                     # unannotated: clean
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert hits and all(f["symbol"] == "Loop.decode" for f in hits)
    # only the unmetered line fires (np.asarray + .numpy on one line)
    assert {f["line"] for f in hits} == {min(f["line"] for f in hits)}


def test_host_sync_transitive_helper(tmp_path):
    """The dispatch-path hazard: a readback hidden one call away from a
    @hot_path function must fire (with the call chain named), while the
    reduced-strictness transitive scan skips the np.asarray heuristic
    (helpers legitimately shape host arrays) and honors the metered
    escape hatch."""
    _write(tmp_path, "mod.py", """
        import numpy as np

        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Engine:
            @hot_path
            def _dispatch_decode(self, t):
                return self._stage(t)

            def _stage(self, t):
                host = np.asarray([1, 2])        # host shaping: clean
                pos = np.asarray(host)           # heuristic off: clean
                return t.numpy(), pos            # unmetered sync: fires

            def _metered(self, t):
                with self.stall.timed("drain"):
                    return t.numpy()             # metered: clean

            @hot_path
            def _commit(self, t):
                return self._metered(t)

            def _unreached(self, t):
                return t.numpy()                 # not on a hot path: clean
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert len(hits) == 1
    assert hits[0]["symbol"] == "Engine._stage"
    assert "reached from @hot_path via Engine._dispatch_decode" \
        in hits[0]["message"]


def test_guarded_by_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        def guarded_by(lock):
            return lock

        def holds_lock(lock):
            def mark(f):
                return f
            return mark

        class Ring:
            _items: guarded_by("_lock")

            def __init__(self):
                self._lock = threading.Lock()
                self._items = []                 # exempt: __init__

            def bad_push(self, x):
                self._items.append(x)            # unguarded

            def good_push(self, x):
                with self._lock:
                    self._items.append(x)

            @holds_lock("_lock")
            def _pop_locked(self):
                return self._items.pop()         # caller holds the lock

        class SubRing(Ring):
            def bad_sub(self):
                return len(self._items)          # inherited declaration
    """)
    report = _lint(tmp_path, rules=["guarded-by"])
    hits = _rules_hit(report, "guarded-by")
    assert {f["symbol"] for f in hits} == {"Ring.bad_push", "SubRing.bad_sub"}


def test_donation_alias_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        class Step:
            def __init__(self, fn, donate):
                self._donate_argnums = (0, 2) if donate else ()
                self._jitted = jax.jit(
                    fn, donate_argnums=self._donate_argnums)

            def bad(self, x, y, z):
                out = self._jitted(x, y, z)
                return out + x               # x (argnum 0) re-read

            def good(self, x, y, z):
                out = self._jitted(x, y, z)
                x = out * 2                  # rebind kills the taint
                return x + y                 # y (argnum 1) is not donated
    """)
    report = _lint(tmp_path, rules=["donation-alias"])
    hits = _rules_hit(report, "donation-alias")
    assert [f["symbol"] for f in hits] == ["Step.bad"]
    assert "`x`" in hits[0]["message"]


# ------------------------------------------------- suppressions + baseline

def test_swallowed_exception_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import logging

        def bare(x):
            try:
                return x()
            except:                       # bad: bare except, no re-raise
                pass

        def broad_silent(x):
            try:
                return x()
            except Exception:             # bad: swallows silently
                pass

        def broad_tuple(x):
            try:
                return x()
            except (ValueError, Exception):   # bad: tuple hides the broad
                pass

        def bare_reraise(x):
            try:
                return x()
            except:                       # clean: re-raises
                raise

        def broad_handled(x):
            try:
                return x()
            except Exception as e:        # clean: the handler DOES something
                logging.warning("x failed: %s", e)
                return None

        def narrow(x):
            try:
                return x()
            except ValueError:            # clean: narrow type may be silent
                pass
    """)
    report = _lint(tmp_path, rules=["swallowed-exception"])
    hits = _rules_hit(report, "swallowed-exception")
    symbols = {f["symbol"] for f in hits}
    assert symbols == {"bare", "broad_silent", "broad_tuple"}
    assert all(f["line"] > 0 for f in hits)

    _write(tmp_path, "mod.py", """
        def f(x):
            try:
                return x()
            # graft-lint: disable-next=swallowed-exception (fixture: the
            # teardown path must not crash)
            except Exception:
                pass
    """)
    report = _lint(tmp_path, rules=["swallowed-exception"])
    assert report["ok"] and report["counts"]["suppressed"] == 1


def test_ledger_bypass_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np
        import paddle_tpu as paddle

        class BypassingPool:
            def __init__(self, n):
                # bad: device pool allocation, class never touches the
                # ledger -> device_memory_bytes census under-counts
                self._pools = [paddle.zeros([n, 16], dtype="float32")]

        class AccountedPool:
            def __init__(self, n, ledger):
                self._pools = [paddle.zeros([n, 16], dtype="float32")]
                self._ledger_handle = ledger.register(
                    "kv_pool", "pools", n * 16 * 4)

        class HostSidePool:
            def __init__(self, n):
                # clean: numpy is host memory, not a device allocation
                self._pool = np.zeros((n, 16), np.float32)

        class PoolingLayer:
            def __init__(self):
                # clean: an nn pooling layer, not an array allocation
                self.avg_pool = object()
    """)
    report = _lint(tmp_path, rules=["ledger-bypass"])
    hits = _rules_hit(report, "ledger-bypass")
    assert len(hits) == 1
    assert hits[0]["symbol"].endswith("BypassingPool")
    assert "BypassingPool" in hits[0]["message"]
    assert hits[0]["line"] > 0

    # staging-marker spelling is covered too
    _write(tmp_path, "mod.py", """
        import jax.numpy as jnp

        class Snapshotter:
            def grab(self, tree):
                self._staging = jnp.zeros((4,))   # bad: unledgered staging
    """)
    report = _lint(tmp_path, rules=["ledger-bypass"])
    assert len(_rules_hit(report, "ledger-bypass")) == 1


def test_suppression_forms(tmp_path):
    _write(tmp_path, "mod.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                a = t.numpy()  # graft-lint: disable=host-sync-in-hot-loop
                # graft-lint: disable-next=host-sync-in-hot-loop (reason
                # may span further comment lines before the code line)
                b = t.numpy()
                c = t.numpy()
                return a, b, c
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert len(hits) == 1                 # only the un-suppressed line
    assert report["counts"]["suppressed"] == 2

    _write(tmp_path, "mod.py", """
        # graft-lint: disable-file=host-sync-in-hot-loop
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    assert report["ok"]
    assert report["counts"]["suppressed"] == 1


def test_baseline_round_trip(tmp_path):
    src = """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """
    _write(tmp_path, "mod.py", src)
    bl = tmp_path / "baseline.json"
    report = run_lint(str(tmp_path), [str(tmp_path)],
                      baseline_path=str(bl))
    assert not report["ok"]
    Baseline.write(str(bl), report["_finding_objs"])

    # same findings -> absorbed, exit clean
    report2 = _lint(tmp_path, baseline=str(bl))
    assert report2["ok"]
    assert report2["counts"]["baselined"] == 1

    # a NEW finding of the same rule/file is NOT absorbed (counted entries)
    _write(tmp_path, "mod.py", src + """
            @hot_path
            def g(self, t):
                return t.numpy()
    """)
    report3 = _lint(tmp_path, baseline=str(bl))
    assert not report3["ok"]
    assert report3["counts"]["baselined"] == 1
    assert report3["counts"]["failing"] == 1


def test_fingerprint_survives_line_shifts(tmp_path):
    """Baseline entries are line-free: edits above a finding don't
    invalidate it."""
    _write(tmp_path, "mod.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    bl = tmp_path / "baseline.json"
    report = run_lint(str(tmp_path), [str(tmp_path)], baseline_path=str(bl))
    Baseline.write(str(bl), report["_finding_objs"])
    _write(tmp_path, "mod.py", """
        # a new comment block
        # shifting every line below it
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    report2 = _lint(tmp_path, baseline=str(bl))
    assert report2["ok"] and report2["counts"]["baselined"] == 1


def test_span_checker_runs_in_suite():
    """The folded-in sixth checker reconciles the real manifest through
    the one lint entry point."""
    report = run_lint(REPO, [os.path.join(REPO, "paddle_tpu")],
                      rules=["span-manifest"])
    report.pop("_finding_objs")
    assert report["ok"], report["findings"]
    assert report["rules"] == ["span-manifest"]


# ----------------------------------------------- acceptance: both directions

def test_lint_repo_exits_zero():
    """Direction 1: the shipped tree is clean (every finding fixed,
    suppressed with a reason, or explicitly baselined)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-3000:]
    rep = json.loads(r.stdout)
    assert rep["ok"] and rep["files_scanned"] > 200
    assert len(rep["rules"]) == 12
    assert rep["schema"] == "graft-lint-report/2"
    assert rep["audits"] == ["stale-suppression"]
    # every reported finding carries a content-addressed fingerprint
    for f in rep["findings"]:
        assert len(f["fingerprint"]) == 16
        int(f["fingerprint"], 16)


def test_lint_catches_seeded_bad_construct(tmp_path):
    """Direction 2: a known-bad construct (unguarded guarded_by write, and
    a .item() in a hot decode loop) exits non-zero with correct
    file:line findings."""
    src = textwrap.dedent("""
        import threading

        def guarded_by(lock):
            return lock

        def hot_path(fn):
            return fn

        class Sched:
            _slots: guarded_by("_lock")

            def __init__(self):
                self._lock = threading.Lock()
                self._slots = []

            @hot_path
            def _decode_once(self, next_ids):
                self._slots.append(1)
                return next_ids.item()
    """)
    bad = tmp_path / "bad.py"
    bad.write_text(src)
    lines = src.splitlines()
    slots_line = lines.index("        self._slots.append(1)") + 1
    item_line = lines.index("        return next_ids.item()") + 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert f"bad.py:{slots_line}" in r.stdout       # guarded-by
    assert f"bad.py:{item_line}" in r.stdout        # host-sync-in-hot-loop
    assert "[guarded-by]" in r.stdout
    assert "[host-sync-in-hot-loop]" in r.stdout


def test_lint_seeded_dispatch_helper_sync_both_directions(tmp_path):
    """The async-engine shape, pinned both ways through the real driver:
    a helper called from the hot dispatch path that syncs unmetered exits
    non-zero with the helper's file:line; metering the same sync under
    stall.timed makes the tree exit zero."""
    tmpl = textwrap.dedent("""
        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Engine:
            @hot_path
            def _dispatch_decode(self, t):
                return self._fetch(t)

            def _fetch(self, t):
                %s
    """)
    bad_body = "return t.numpy()"
    good_body = ("with self.stall.timed(\"drain\"):\n"
                 "            return t.numpy()")
    bad = tmp_path / "engine.py"
    bad.write_text(tmpl % bad_body)
    line = (tmpl % bad_body).splitlines().index(
        f"        {bad_body}") + 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert f"engine.py:{line}" in r.stdout
    assert "[host-sync-in-hot-loop]" in r.stdout
    assert "reached from @hot_path" in r.stdout

    bad.write_text(tmpl % good_body)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-2000:]


def test_changed_mode_scopes_findings(tmp_path):
    """--changed machinery: findings restricted to the given file set."""
    _write(tmp_path, "one.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    _write(tmp_path, "two.py", """
        def hot_path(fn):
            return fn

        class B:
            @hot_path
            def g(self, t):
                return t.numpy()
    """)
    report = run_lint(str(tmp_path), [str(tmp_path)],
                      baseline_path=str(tmp_path / "bl.json"),
                      changed_files=["one.py"])
    report.pop("_finding_objs")
    assert {f["file"] for f in report["findings"]} == {"one.py"}


def test_parse_error_is_a_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    report = _lint(tmp_path)
    assert not report["ok"]
    assert report["findings"][0]["rule"] == "parse-error"


def test_module_suppression_parsing():
    m = Module("x.py", "x.py",
               "a = 1  # graft-lint: disable=r1,r2\n"
               "# graft-lint: disable-file=r3\n")
    assert m.is_suppressed("r1", 1) and m.is_suppressed("r2", 1)
    assert not m.is_suppressed("r1", 2)
    assert m.is_suppressed("r3", 99)     # file-wide, any line


# ------------------------------------------ regressions from the first run

def test_registry_scrape_during_metric_creation_regression():
    """FIXED by this PR: MetricsRegistry.snapshot()/prometheus_text() read
    ``_metrics`` (and label families read ``_children``) without the lock,
    so an endpoint scrape racing lazy metric creation died with
    "OrderedDict mutated during iteration". Hammer both sides."""
    from paddle_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry("lint_regression")
    errs = []
    stop = threading.Event()

    def creator():
        i = 0
        fam = reg.counter("family")
        while not stop.is_set() and i < 30000:
            reg.counter(f"c{i}").inc()
            fam.labels(k=str(i)).inc()
            if i % 3 == 0:
                reg.histogram(f"h{i}").record(i)
            i += 1

    def scraper():
        try:
            while not stop.is_set():
                reg.snapshot()
                reg.prometheus_text()
        except RuntimeError as e:        # the pre-fix failure mode
            errs.append(e)

    threads = [threading.Thread(target=creator, daemon=True),
               threading.Thread(target=scraper, daemon=True),
               threading.Thread(target=scraper, daemon=True)]
    for t in threads:
        t.start()
    threads[0].join(timeout=30)
    stop.set()
    for t in threads[1:]:
        t.join(timeout=10)
    assert not errs, f"scrape raced metric creation: {errs[0]!r}"


def test_histogram_concurrent_record_is_exact():
    """FIXED by this PR: Histogram had no lock — concurrent record() lost
    count/total updates and the reservoir raced summary()'s numpy read.
    With the lock, count/total are exact under contention."""
    from paddle_tpu.observability.metrics import Histogram

    h = Histogram(max_samples=256)
    N, T = 20000, 4
    errs = []

    def writer():
        try:
            for i in range(N):
                h.record(1.0)
                if i % 500 == 0:
                    h.summary()
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    assert h.count == N * T
    assert h.total == float(N * T)
    assert h.summary()["count"] == N * T


def test_flight_recorder_concurrent_alarm_and_dump():
    """FIXED by this PR: FlightRecorder.__len__/alarm touched the ring and
    the frozen alarm snapshot without the lock."""
    from paddle_tpu.observability.serving_stall import FlightRecorder

    fr = FlightRecorder(max_steps=64)
    errs = []

    def stepper():
        try:
            for i in range(5000):
                fr.record_step(i=i)
                if i % 50 == 0:
                    fr.alarm("test", f"at {i}")
        except Exception as e:
            errs.append(e)

    def reader():
        try:
            for _ in range(2000):
                len(fr)
                fr.dump(last=8)
                _ = fr.last_alarm_dump
                _ = fr.steps_recorded
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=stepper),
               threading.Thread(target=reader),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    assert fr.steps_recorded == 5000
    assert fr.last_alarm_dump is not None
    assert fr.last_alarm_dump["kind"] == "test"


def test_request_tracer_get_concurrent_with_finish():
    """FIXED by this PR: RequestTracer.get() read the live/done dicts
    without the lock while finish() rebalanced them."""
    from paddle_tpu.observability.request_trace import RequestTracer

    tr = RequestTracer(enabled=True, max_completed=32)
    errs = []

    def lifecycle():
        try:
            for i in range(4000):
                tr.start(i)
                tr.finish(i)
        except Exception as e:
            errs.append(e)

    def getter():
        try:
            for i in range(8000):
                tr.get(i % 4000)
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=lifecycle),
               threading.Thread(target=getter)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs


def test_annotations_are_runtime_inert():
    from paddle_tpu.observability.annotations import (
        guarded_by,
        holds_lock,
        hot_path,
        lock_order,
        thread_role,
    )

    @hot_path
    def f():
        return 41

    @hot_path(reason="why")
    def g():
        return 42

    @holds_lock("_lock")
    def h():
        return 43

    @thread_role("drain")
    def k():
        return 44

    assert f() == 41 and g() == 42 and h() == 43 and k() == 44
    assert f.__graft_hot_path__ is True
    assert g.__graft_hot_path__ == "why"
    assert h.__graft_holds_lock__ == "_lock"
    assert k.__graft_thread_role__ == "drain"
    assert guarded_by("_lock").lock == "_lock"
    assert "guarded_by" in repr(guarded_by("_lock"))
    decl = lock_order("A._la", "<", "B._lb")
    assert decl.first == "A._la" and decl.second == "B._lb"
    with pytest.raises(ValueError):
        lock_order("A._la", ">", "B._lb")   # only "<" is a valid op


def test_bench_json_canonicalization(tmp_path):
    """Satellite: bench artifacts write with sorted keys + stable floats,
    so a no-change re-run is a no-diff."""
    from tools.bench_io import canonical, write_bench_json

    art_a = {"b": 0.1 + 0.2, "a": [3.0, {"z": 1, "y": 2.0000000001}],
             "n": None, "t": True}
    art_b = {"t": True, "n": None,
             "a": [3, {"y": 2.0, "z": 1}], "b": 0.3}
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    write_bench_json(str(p1), art_a)
    write_bench_json(str(p2), art_b)
    assert p1.read_text() == p2.read_text()      # byte-identical
    assert canonical(float("nan")) == "nan"
    assert canonical(0.123456789) == 0.123457
    assert canonical(66.0) == 66
    assert json.loads(p1.read_text())["b"] == 0.3


# ------------------------------------- concurrency checkers (PR: lint-conc)

def test_lock_order_cycle_bad_and_clean(tmp_path):
    """ABBA inversion across two methods is a lock-order cycle; a
    consistent nesting order is clean."""
    src = """
        import threading

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def ab(self):
                with self._la:
                    with self._lb:
                        pass

            def ba(self):
                with self._lb:
                    with self._la:
                        pass
    """
    _write(tmp_path, "bad_cycle.py", src)
    report = _lint(tmp_path, rules=["lock-order"])
    hits = _rules_hit(report, "lock-order")
    assert hits, report["findings"]
    assert "cycle" in hits[0]["message"]
    inner_lines = [i + 1 for i, ln in
                   enumerate(textwrap.dedent(src).splitlines())
                   if ln.strip() in ("with self._lb:", "with self._la:")
                   and "    with" in ln[8:]]
    # the finding anchors at one of the two inner (second) acquisitions
    assert any(h["line"] in inner_lines for h in hits), (hits, inner_lines)

    (tmp_path / "bad_cycle.py").unlink()
    _write(tmp_path, "clean_order.py", """
        import threading

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def ab(self):
                with self._la:
                    with self._lb:
                        pass

            def ab2(self):
                with self._la:
                    with self._lb:
                        pass
    """)
    report = _lint(tmp_path, rules=["lock-order"])
    assert not _rules_hit(report, "lock-order")


def test_lock_order_transitive_cycle_through_helper(tmp_path):
    """The inversion hides behind a call: f holds A and calls g, which
    takes B while another path nests B then A. The whole-program
    may-acquire propagation still finds the cycle."""
    _write(tmp_path, "transitive_cycle.py", """
        import threading

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def f(self):
                with self._la:
                    self._takes_b()

            def _takes_b(self):
                with self._lb:
                    pass

            def ba(self):
                with self._lb:
                    with self._la:
                        pass
    """)
    report = _lint(tmp_path, rules=["lock-order"])
    hits = _rules_hit(report, "lock-order")
    assert hits and "cycle" in hits[0]["message"]


def test_lock_order_declaration_enforced(tmp_path):
    """A checked ``lock_order`` declaration: acquiring the declared-first
    lock while holding the declared-second one is a violation at the
    acquisition site; the compliant nesting is clean, and a declaration
    naming a lock that does not exist is itself a finding."""
    src = """
        import threading

        def lock_order(first, op, second):
            return (first, op, second)

        lock_order("Pair._la", "<", "Pair._lb")

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def bad(self):
                with self._lb:
                    with self._la:
                        pass
    """
    _write(tmp_path, "decl_violation.py", src)
    report = _lint(tmp_path, rules=["lock-order"])
    hits = _rules_hit(report, "lock-order")
    assert hits, report["findings"]
    viol = [h for h in hits if "declared" in h["message"]
            or "lock_order" in h["message"]]
    assert viol
    bad_line = [i + 1 for i, ln in
                enumerate(textwrap.dedent(src).splitlines())
                if ln.strip() == "with self._la:"][0]
    assert any(h["line"] == bad_line for h in viol), (viol, bad_line)

    (tmp_path / "decl_violation.py").unlink()
    _write(tmp_path, "decl_clean.py", """
        import threading

        def lock_order(first, op, second):
            return (first, op, second)

        lock_order("Pair._la", "<", "Pair._lb")

        class Pair:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def good(self):
                with self._la:
                    with self._lb:
                        pass
    """)
    report = _lint(tmp_path, rules=["lock-order"])
    assert not _rules_hit(report, "lock-order")

    (tmp_path / "decl_clean.py").unlink()
    _write(tmp_path, "decl_unknown.py", """
        def lock_order(first, op, second):
            return (first, op, second)

        lock_order("Ghost._lock", "<", "Phantom._lock")
    """)
    report = _lint(tmp_path, rules=["lock-order"])
    hits = _rules_hit(report, "lock-order")
    assert hits and "unknown lock" in hits[0]["message"]


def test_thread_role_two_role_write_bad_and_clean(tmp_path):
    """A spawn target writing an undeclared attribute with no lock held is
    the two-role write; the same write under the lock is clean."""
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.state = 0
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self._run, name="bg")
                self._t.start()

            def _run(self):
                self.state = 1
    """
    _write(tmp_path, "bad_roles.py", src)
    report = _lint(tmp_path, rules=["thread-role"])
    hits = _rules_hit(report, "thread-role")
    assert hits, report["findings"]
    bad_line = [i + 1 for i, ln in
                enumerate(textwrap.dedent(src).splitlines())
                if ln.strip() == "self.state = 1"][0]
    assert hits[0]["line"] == bad_line
    assert "'bg'" in hits[0]["message"]
    assert "guarded_by" in hits[0]["message"]

    (tmp_path / "bad_roles.py").unlink()
    _write(tmp_path, "clean_roles.py", """
        import threading

        class Worker:
            def __init__(self):
                self.state = 0
                self._lock = threading.Lock()
                self._t = threading.Thread(target=self._run, name="bg")
                self._t.start()

            def _run(self):
                with self._lock:
                    self.state = 1
    """)
    report = _lint(tmp_path, rules=["thread-role"])
    assert not _rules_hit(report, "thread-role")


def test_thread_role_propagates_through_calls(tmp_path):
    """The write sits two calls below the spawn target; role reachability
    still tags it."""
    _write(tmp_path, "deep_roles.py", """
        import threading

        class Worker:
            def __init__(self):
                self.n = 0
                self._t = threading.Thread(target=self._run, name="drain")
                self._t.start()

            def _run(self):
                self._step()

            def _step(self):
                self.n += 1
    """)
    report = _lint(tmp_path, rules=["thread-role"])
    hits = _rules_hit(report, "thread-role")
    assert hits and "`self.n`" in hits[0]["message"]
    assert "'drain'" in hits[0]["message"]


def test_blocking_under_lock_bad_and_clean(tmp_path):
    """sleep/join/queue-get under a held lock is flagged at the blocking
    call; bounded waits and metered stalls escape."""
    src = """
        import queue
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()
                self._t = threading.Thread(target=self._run)

            def _run(self):
                pass

            def bad_sleep(self):
                with self._lock:
                    time.sleep(0.1)

            def bad_join(self):
                with self._lock:
                    self._t.join()

            def bad_queue(self):
                with self._lock:
                    return self._q.get()
    """
    _write(tmp_path, "bad_blocking.py", src)
    report = _lint(tmp_path, rules=["blocking-under-lock"])
    hits = _rules_hit(report, "blocking-under-lock")
    lines = textwrap.dedent(src).splitlines()
    for needle in ("time.sleep(0.1)", "self._t.join()",
                   "return self._q.get()"):
        ln = [i + 1 for i, s in enumerate(lines) if s.strip() == needle][0]
        assert any(h["line"] == ln for h in hits), (needle, hits)
    assert all("Box._lock" in h["message"] for h in hits)

    (tmp_path / "bad_blocking.py").unlink()
    _write(tmp_path, "clean_blocking.py", """
        import queue
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()
                self._t = threading.Thread(target=self._run)
                self.stall = None

            def _run(self):
                pass

            def sleep_outside(self):
                with self._lock:
                    n = 1
                time.sleep(0.1)
                return n

            def bounded_join(self):
                with self._lock:
                    self._t.join(timeout=1.0)

            def bounded_queue(self):
                with self._lock:
                    return self._q.get(timeout=0.5)

            def metered(self):
                with self._lock:
                    with self.stall.timed("drain"):
                        time.sleep(0.1)
    """)
    report = _lint(tmp_path, rules=["blocking-under-lock"])
    assert not _rules_hit(report, "blocking-under-lock")


def test_blocking_under_lock_transitive_through_helper(tmp_path):
    """The sleep hides in a helper; the lock-held call site is flagged
    with the chain to the origin."""
    src = """
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def caller(self):
                with self._lock:
                    self._nap()

            def _nap(self):
                time.sleep(0.5)
    """
    _write(tmp_path, "transitive_block.py", src)
    report = _lint(tmp_path, rules=["blocking-under-lock"])
    hits = _rules_hit(report, "blocking-under-lock")
    assert hits, report["findings"]
    call_line = [i + 1 for i, ln in
                 enumerate(textwrap.dedent(src).splitlines())
                 if ln.strip() == "self._nap()"][0]
    assert hits[0]["line"] == call_line
    assert "may block" in hits[0]["message"]
    assert "_nap" in hits[0]["message"]


def test_condition_wait_on_held_lock_is_not_blocking(tmp_path):
    """``cond.wait()`` on the lock you hold RELEASES it while sleeping —
    the scheduler's backoff idiom must stay clean."""
    _write(tmp_path, "cond_wait.py", """
        import threading

        class Engine:
            def __init__(self):
                self._elock = threading.Condition(threading.RLock())

            def backoff(self):
                with self._elock:
                    self._elock.wait(0.2)
    """)
    report = _lint(tmp_path, rules=["blocking-under-lock"])
    assert not _rules_hit(report, "blocking-under-lock")


def test_stale_suppression_audit(tmp_path):
    """A ``disable`` comment that silences nothing is flagged; one that
    suppresses a real finding is not; a docstring that merely MENTIONS
    the directive syntax is not audited."""
    src = '''
        """Module doc. Example: # graft-lint: disable=guarded-by inline."""
        import threading

        def guarded_by(lock):
            return lock

        class A:
            _x: guarded_by("_lock")

            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def bad(self):
                self._x = 1  # graft-lint: disable=guarded-by

            def fine(self):
                return 2  # graft-lint: disable=guarded-by
    '''
    _write(tmp_path, "stale.py", src)
    report = _lint(tmp_path)          # full run: the audit is active
    stale = _rules_hit(report, "stale-suppression")
    assert len(stale) == 1, report["findings"]
    dead_line = [i + 1 for i, ln in
                 enumerate(textwrap.dedent(src).splitlines())
                 if "return 2" in ln][0]
    assert stale[0]["line"] == dead_line
    assert "matches no finding" in stale[0]["message"]
    # the used suppression still works: no unsuppressed guarded-by finding
    assert not _rules_hit(report, "guarded-by")


def test_stale_audit_skipped_on_partial_runs(tmp_path):
    """``disable=all`` can only be audited when every rule ran; a rules
    subset must not flag it."""
    _write(tmp_path, "partial.py", """
        def f():
            return 1  # graft-lint: disable=all
    """)
    report = _lint(tmp_path, rules=["guarded-by"])
    assert not _rules_hit(report, "stale-suppression")
    report = _lint(tmp_path)
    assert len(_rules_hit(report, "stale-suppression")) == 1


def test_rules_concurrency_group_alias(tmp_path):
    """--rules concurrency expands to the four concurrency rules."""
    from tools.graft_lint import RULE_GROUPS, expand_rules

    _write(tmp_path, "empty.py", "x = 1\n")
    report = _lint(tmp_path, rules=["concurrency"])
    assert set(report["rules"]) == {"lock-order", "thread-role",
                                    "blocking-under-lock", "guarded-by"}
    assert report["audits"] == []     # the audit needs a full run
    assert expand_rules(["concurrency", "guarded-by"]) \
        == list(RULE_GROUPS["concurrency"])
    assert expand_rules(None) is None


def test_lint_seeded_concurrency_bad_constructs(tmp_path):
    """Acceptance direction 2 for the new checkers, through the real
    driver: a seeded sleep-under-lock, an undeclared two-role write, and
    a lock-order inversion exit non-zero with correct file:line."""
    src = textwrap.dedent("""
        import threading
        import time

        class Bad:
            def __init__(self):
                self.count = 0
                self._la = threading.Lock()
                self._lb = threading.Lock()
                self._t = threading.Thread(target=self._drain, name="drain")

            def _drain(self):
                self.count += 1

            def sleepy(self):
                with self._la:
                    time.sleep(0.1)

            def ab(self):
                with self._la:
                    with self._lb:
                        pass

            def ba(self):
                with self._lb:
                    with self._la:
                        pass
    """)
    bad = tmp_path / "bad_conc.py"
    bad.write_text(src)
    lines = src.splitlines()
    write_line = lines.index("        self.count += 1") + 1
    sleep_line = lines.index("            time.sleep(0.1)") + 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--rules", "concurrency",
         "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert f"bad_conc.py:{write_line}" in r.stdout     # thread-role
    assert f"bad_conc.py:{sleep_line}" in r.stdout     # blocking-under-lock
    assert "[thread-role]" in r.stdout
    assert "[blocking-under-lock]" in r.stdout
    assert "[lock-order]" in r.stdout


# ---------------------------- regressions from the concurrency-rule triage

def _rpc_double(x):
    return x * 2


class _FakeKV:
    """In-memory TCPStore lookalike for driving _RpcAgent in-process."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = v

    def get(self, k):
        with self._lock:
            return self._d[k]

    def check(self, k):
        with self._lock:
            return k in self._d

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)

    def add(self, k, n):
        with self._lock:
            v = int(self._d.get(k, 0)) + n
            self._d[k] = v
            return v

    def wait(self, k):
        pass


def test_rpc_future_table_locked_handoff_regression():
    """FIXED by this PR (found by the thread-role rule): ``_RpcAgent``'s
    outstanding-call table was inserted by caller threads and swept by
    the poller with NO lock — a caller's dict insert racing the poller's
    iteration killed the poll thread with RuntimeError and every future
    after it timed out. Hammer both sides through a self-call loop."""
    from paddle_tpu.distributed.rpc import _RpcAgent

    agent = _RpcAgent("w0", 0, 1, _FakeKV())
    try:
        results, errs = {}, []

        def caller(base):
            try:
                futs = [(base + i,
                         agent.call(0, _rpc_double, (base + i,), {}))
                        for i in range(25)]
                for x, fut in futs:
                    results[x] = fut.wait(timeout=60)
            except Exception as e:
                errs.append(e)

        threads = [threading.Thread(target=caller, args=(1000 * t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs[0]
        assert len(results) == 100
        assert all(results[x] == 2 * x for x in results)
        with agent._flock:
            assert not agent._futures   # every future swept exactly once
    finally:
        agent.shutdown()


def test_sparse_table_save_is_consistent_snapshot_regression(tmp_path):
    """FIXED by this PR (found by the blocking-under-lock rule):
    ``MemorySparseTable.save`` pickled to disk while HOLDING the table
    lock, stalling every pull/push for the file I/O. It now snapshots
    row COPIES under the lock and serialises outside — saves racing
    in-place row mutation must load back complete, well-formed tables."""
    import pickle

    import numpy as np

    from paddle_tpu.distributed.ps import MemorySparseTable

    table = MemorySparseTable(0, dim=4)
    stop = threading.Event()
    errs = []

    def pusher():
        try:
            i = 0
            while not stop.is_set():
                ids = np.arange(32) + (i % 8) * 32
                table.pull(ids)
                grads = np.full((32, 4), 0.01, np.float32)
                table.push(ids, grads)
                i += 1
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=pusher, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        path = str(tmp_path / "table.pkl")
        for _ in range(10):
            table.save(path)
            with open(path, "rb") as f:
                rows = pickle.load(f)
            assert rows              # snapshot is complete + parseable
            for k, v in rows.items():
                assert isinstance(k, int)
                row = np.asarray(v, np.float32)
                assert row.ndim == 1 and np.isfinite(row).all()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errs, errs[0]
