"""graft_lint: the framework-invariant static-analysis suite as tier-1.

Three layers of pinning:

1. Fixture tests per rule — one known-bad and one known-clean snippet per
   checker, run through the real driver machinery (no jax devices needed:
   the suite is stdlib-ast only).
2. Suppression + baseline round trips — ``# graft-lint: disable=...`` in
   its three forms, and the accepted-findings baseline absorbing exactly
   the findings it records (a NEW finding still fails).
3. The acceptance bar, both directions: ``python tools/lint.py`` over the
   real repo exits 0 with zero non-baselined findings, and seeding a
   known-bad construct makes it exit non-zero with a correct file:line.

Plus regression tests for the real bugs the first full-repo run surfaced
(unguarded registry/histogram/flight-recorder state shared with the
ObservabilityEndpoint scrape thread).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graft_lint import Baseline, run_lint  # noqa: E402
from tools.graft_lint.core import Module  # noqa: E402


def _lint(tmp_path, rules=None, baseline=None):
    """Run the suite over the tmp fixture tree; returns (report, findings
    as dicts)."""
    report = run_lint(str(tmp_path), [str(tmp_path)], rules=rules,
                      baseline_path=baseline
                      or str(tmp_path / "no_baseline.json"))
    report.pop("_finding_objs")
    return report


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return p


def _rules_hit(report, rule):
    return [f for f in report["findings"] if f["rule"] == rule
            and not f["suppressed"] and not f["baselined"]]


# ---------------------------------------------------------------- fixtures

def test_tracing_hazard_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import jax.numpy as jnp
        import numpy as np

        def to_static(fn):
            return fn

        def helper(x):
            return x.item() + 1          # hazard, reachable via traced()

        @to_static
        def traced(x):
            if bool(x):                   # hazard: bool() on traced value
                return helper(x)
            return jnp.sum(x)             # clean: stays in jnp

        def eager_only(x):
            return np.asarray(x).item()   # NOT reachable from a trace root
    """)
    report = _lint(tmp_path, rules=["tracing-hazard"])
    hits = _rules_hit(report, "tracing-hazard")
    symbols = {f["symbol"] for f in hits}
    assert "helper" in symbols            # call-graph reachability
    assert "traced" in symbols            # direct hazard in the root
    assert "eager_only" not in symbols    # eager code is out of scope
    assert all(f["file"] == "mod.py" and f["line"] > 0 for f in hits)


def test_recompile_hazard_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np

        def _bucket(n, lo=16):
            b = lo
            while b < n:
                b *= 2
            return b

        class Sched:
            def bad(self, ids):
                P = len(ids)
                a = np.zeros((1, P), np.int32)      # raw data-dep width
                return self._step_fn(a)

            def good(self, ids):
                Pb = min(_bucket(len(ids)), 512)
                a = np.zeros((1, Pb), np.int32)     # bucketed: clean
                return self._step_fn(a)

            def no_jit_here(self, ids):
                return np.zeros((len(ids),))        # no jit callsite: clean
    """)
    report = _lint(tmp_path, rules=["recompile-hazard"])
    hits = _rules_hit(report, "recompile-hazard")
    assert [f["symbol"] for f in hits] == ["Sched.bad"]


def test_host_sync_in_hot_loop_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np

        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Loop:
            @hot_path
            def decode(self, t):
                bad = np.asarray(t.numpy())          # unmetered sync
                with self.stall.timed("sampling_sync"):
                    ok = np.asarray(t.numpy())       # metered: allowed
                return bad, ok

            def not_hot(self, t):
                return t.numpy()                     # unannotated: clean
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert hits and all(f["symbol"] == "Loop.decode" for f in hits)
    # only the unmetered line fires (np.asarray + .numpy on one line)
    assert {f["line"] for f in hits} == {min(f["line"] for f in hits)}


def test_host_sync_transitive_helper(tmp_path):
    """The dispatch-path hazard: a readback hidden one call away from a
    @hot_path function must fire (with the call chain named), while the
    reduced-strictness transitive scan skips the np.asarray heuristic
    (helpers legitimately shape host arrays) and honors the metered
    escape hatch."""
    _write(tmp_path, "mod.py", """
        import numpy as np

        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Engine:
            @hot_path
            def _dispatch_decode(self, t):
                return self._stage(t)

            def _stage(self, t):
                host = np.asarray([1, 2])        # host shaping: clean
                pos = np.asarray(host)           # heuristic off: clean
                return t.numpy(), pos            # unmetered sync: fires

            def _metered(self, t):
                with self.stall.timed("drain"):
                    return t.numpy()             # metered: clean

            @hot_path
            def _commit(self, t):
                return self._metered(t)

            def _unreached(self, t):
                return t.numpy()                 # not on a hot path: clean
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert len(hits) == 1
    assert hits[0]["symbol"] == "Engine._stage"
    assert "reached from @hot_path via Engine._dispatch_decode" \
        in hits[0]["message"]


def test_guarded_by_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        def guarded_by(lock):
            return lock

        def holds_lock(lock):
            def mark(f):
                return f
            return mark

        class Ring:
            _items: guarded_by("_lock")

            def __init__(self):
                self._lock = threading.Lock()
                self._items = []                 # exempt: __init__

            def bad_push(self, x):
                self._items.append(x)            # unguarded

            def good_push(self, x):
                with self._lock:
                    self._items.append(x)

            @holds_lock("_lock")
            def _pop_locked(self):
                return self._items.pop()         # caller holds the lock

        class SubRing(Ring):
            def bad_sub(self):
                return len(self._items)          # inherited declaration
    """)
    report = _lint(tmp_path, rules=["guarded-by"])
    hits = _rules_hit(report, "guarded-by")
    assert {f["symbol"] for f in hits} == {"Ring.bad_push", "SubRing.bad_sub"}


def test_donation_alias_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import jax

        class Step:
            def __init__(self, fn, donate):
                self._donate_argnums = (0, 2) if donate else ()
                self._jitted = jax.jit(
                    fn, donate_argnums=self._donate_argnums)

            def bad(self, x, y, z):
                out = self._jitted(x, y, z)
                return out + x               # x (argnum 0) re-read

            def good(self, x, y, z):
                out = self._jitted(x, y, z)
                x = out * 2                  # rebind kills the taint
                return x + y                 # y (argnum 1) is not donated
    """)
    report = _lint(tmp_path, rules=["donation-alias"])
    hits = _rules_hit(report, "donation-alias")
    assert [f["symbol"] for f in hits] == ["Step.bad"]
    assert "`x`" in hits[0]["message"]


# ------------------------------------------------- suppressions + baseline

def test_swallowed_exception_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import logging

        def bare(x):
            try:
                return x()
            except:                       # bad: bare except, no re-raise
                pass

        def broad_silent(x):
            try:
                return x()
            except Exception:             # bad: swallows silently
                pass

        def broad_tuple(x):
            try:
                return x()
            except (ValueError, Exception):   # bad: tuple hides the broad
                pass

        def bare_reraise(x):
            try:
                return x()
            except:                       # clean: re-raises
                raise

        def broad_handled(x):
            try:
                return x()
            except Exception as e:        # clean: the handler DOES something
                logging.warning("x failed: %s", e)
                return None

        def narrow(x):
            try:
                return x()
            except ValueError:            # clean: narrow type may be silent
                pass
    """)
    report = _lint(tmp_path, rules=["swallowed-exception"])
    hits = _rules_hit(report, "swallowed-exception")
    symbols = {f["symbol"] for f in hits}
    assert symbols == {"bare", "broad_silent", "broad_tuple"}
    assert all(f["line"] > 0 for f in hits)

    _write(tmp_path, "mod.py", """
        def f(x):
            try:
                return x()
            # graft-lint: disable-next=swallowed-exception (fixture: the
            # teardown path must not crash)
            except Exception:
                pass
    """)
    report = _lint(tmp_path, rules=["swallowed-exception"])
    assert report["ok"] and report["counts"]["suppressed"] == 1


def test_ledger_bypass_bad_and_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import numpy as np
        import paddle_tpu as paddle

        class BypassingPool:
            def __init__(self, n):
                # bad: device pool allocation, class never touches the
                # ledger -> device_memory_bytes census under-counts
                self._pools = [paddle.zeros([n, 16], dtype="float32")]

        class AccountedPool:
            def __init__(self, n, ledger):
                self._pools = [paddle.zeros([n, 16], dtype="float32")]
                self._ledger_handle = ledger.register(
                    "kv_pool", "pools", n * 16 * 4)

        class HostSidePool:
            def __init__(self, n):
                # clean: numpy is host memory, not a device allocation
                self._pool = np.zeros((n, 16), np.float32)

        class PoolingLayer:
            def __init__(self):
                # clean: an nn pooling layer, not an array allocation
                self.avg_pool = object()
    """)
    report = _lint(tmp_path, rules=["ledger-bypass"])
    hits = _rules_hit(report, "ledger-bypass")
    assert len(hits) == 1
    assert hits[0]["symbol"].endswith("BypassingPool")
    assert "BypassingPool" in hits[0]["message"]
    assert hits[0]["line"] > 0

    # staging-marker spelling is covered too
    _write(tmp_path, "mod.py", """
        import jax.numpy as jnp

        class Snapshotter:
            def grab(self, tree):
                self._staging = jnp.zeros((4,))   # bad: unledgered staging
    """)
    report = _lint(tmp_path, rules=["ledger-bypass"])
    assert len(_rules_hit(report, "ledger-bypass")) == 1


def test_suppression_forms(tmp_path):
    _write(tmp_path, "mod.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                a = t.numpy()  # graft-lint: disable=host-sync-in-hot-loop
                # graft-lint: disable-next=host-sync-in-hot-loop (reason
                # may span further comment lines before the code line)
                b = t.numpy()
                c = t.numpy()
                return a, b, c
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    hits = _rules_hit(report, "host-sync-in-hot-loop")
    assert len(hits) == 1                 # only the un-suppressed line
    assert report["counts"]["suppressed"] == 2

    _write(tmp_path, "mod.py", """
        # graft-lint: disable-file=host-sync-in-hot-loop
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    report = _lint(tmp_path, rules=["host-sync-in-hot-loop"])
    assert report["ok"]
    assert report["counts"]["suppressed"] == 1


def test_baseline_round_trip(tmp_path):
    src = """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """
    _write(tmp_path, "mod.py", src)
    bl = tmp_path / "baseline.json"
    report = run_lint(str(tmp_path), [str(tmp_path)],
                      baseline_path=str(bl))
    assert not report["ok"]
    Baseline.write(str(bl), report["_finding_objs"])

    # same findings -> absorbed, exit clean
    report2 = _lint(tmp_path, baseline=str(bl))
    assert report2["ok"]
    assert report2["counts"]["baselined"] == 1

    # a NEW finding of the same rule/file is NOT absorbed (counted entries)
    _write(tmp_path, "mod.py", src + """
            @hot_path
            def g(self, t):
                return t.numpy()
    """)
    report3 = _lint(tmp_path, baseline=str(bl))
    assert not report3["ok"]
    assert report3["counts"]["baselined"] == 1
    assert report3["counts"]["failing"] == 1


def test_fingerprint_survives_line_shifts(tmp_path):
    """Baseline entries are line-free: edits above a finding don't
    invalidate it."""
    _write(tmp_path, "mod.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    bl = tmp_path / "baseline.json"
    report = run_lint(str(tmp_path), [str(tmp_path)], baseline_path=str(bl))
    Baseline.write(str(bl), report["_finding_objs"])
    _write(tmp_path, "mod.py", """
        # a new comment block
        # shifting every line below it
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    report2 = _lint(tmp_path, baseline=str(bl))
    assert report2["ok"] and report2["counts"]["baselined"] == 1


def test_span_checker_runs_in_suite():
    """The folded-in sixth checker reconciles the real manifest through
    the one lint entry point."""
    report = run_lint(REPO, [os.path.join(REPO, "paddle_tpu")],
                      rules=["span-manifest"])
    report.pop("_finding_objs")
    assert report["ok"], report["findings"]
    assert report["rules"] == ["span-manifest"]


# ----------------------------------------------- acceptance: both directions

def test_lint_repo_exits_zero():
    """Direction 1: the shipped tree is clean (every finding fixed,
    suppressed with a reason, or explicitly baselined)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-3000:]
    rep = json.loads(r.stdout)
    assert rep["ok"] and rep["files_scanned"] > 200
    assert len(rep["rules"]) == 8


def test_lint_catches_seeded_bad_construct(tmp_path):
    """Direction 2: a known-bad construct (unguarded guarded_by write, and
    a .item() in a hot decode loop) exits non-zero with correct
    file:line findings."""
    src = textwrap.dedent("""
        import threading

        def guarded_by(lock):
            return lock

        def hot_path(fn):
            return fn

        class Sched:
            _slots: guarded_by("_lock")

            def __init__(self):
                self._lock = threading.Lock()
                self._slots = []

            @hot_path
            def _decode_once(self, next_ids):
                self._slots.append(1)
                return next_ids.item()
    """)
    bad = tmp_path / "bad.py"
    bad.write_text(src)
    lines = src.splitlines()
    slots_line = lines.index("        self._slots.append(1)") + 1
    item_line = lines.index("        return next_ids.item()") + 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert f"bad.py:{slots_line}" in r.stdout       # guarded-by
    assert f"bad.py:{item_line}" in r.stdout        # host-sync-in-hot-loop
    assert "[guarded-by]" in r.stdout
    assert "[host-sync-in-hot-loop]" in r.stdout


def test_lint_seeded_dispatch_helper_sync_both_directions(tmp_path):
    """The async-engine shape, pinned both ways through the real driver:
    a helper called from the hot dispatch path that syncs unmetered exits
    non-zero with the helper's file:line; metering the same sync under
    stall.timed makes the tree exit zero."""
    tmpl = textwrap.dedent("""
        def hot_path(fn=None, **kw):
            def mark(f):
                return f
            return mark if fn is None else fn

        class Engine:
            @hot_path
            def _dispatch_decode(self, t):
                return self._fetch(t)

            def _fetch(self, t):
                %s
    """)
    bad_body = "return t.numpy()"
    good_body = ("with self.stall.timed(\"drain\"):\n"
                 "            return t.numpy()")
    bad = tmp_path / "engine.py"
    bad.write_text(tmpl % bad_body)
    line = (tmpl % bad_body).splitlines().index(
        f"        {bad_body}") + 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode != 0
    assert f"engine.py:{line}" in r.stdout
    assert "[host-sync-in-hot-loop]" in r.stdout
    assert "reached from @hot_path" in r.stdout

    bad.write_text(tmpl % good_body)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-2000:]


def test_changed_mode_scopes_findings(tmp_path):
    """--changed machinery: findings restricted to the given file set."""
    _write(tmp_path, "one.py", """
        def hot_path(fn):
            return fn

        class A:
            @hot_path
            def f(self, t):
                return t.numpy()
    """)
    _write(tmp_path, "two.py", """
        def hot_path(fn):
            return fn

        class B:
            @hot_path
            def g(self, t):
                return t.numpy()
    """)
    report = run_lint(str(tmp_path), [str(tmp_path)],
                      baseline_path=str(tmp_path / "bl.json"),
                      changed_files=["one.py"])
    report.pop("_finding_objs")
    assert {f["file"] for f in report["findings"]} == {"one.py"}


def test_parse_error_is_a_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    report = _lint(tmp_path)
    assert not report["ok"]
    assert report["findings"][0]["rule"] == "parse-error"


def test_module_suppression_parsing():
    m = Module("x.py", "x.py",
               "a = 1  # graft-lint: disable=r1,r2\n"
               "# graft-lint: disable-file=r3\n")
    assert m.is_suppressed("r1", 1) and m.is_suppressed("r2", 1)
    assert not m.is_suppressed("r1", 2)
    assert m.is_suppressed("r3", 99)     # file-wide, any line


# ------------------------------------------ regressions from the first run

def test_registry_scrape_during_metric_creation_regression():
    """FIXED by this PR: MetricsRegistry.snapshot()/prometheus_text() read
    ``_metrics`` (and label families read ``_children``) without the lock,
    so an endpoint scrape racing lazy metric creation died with
    "OrderedDict mutated during iteration". Hammer both sides."""
    from paddle_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry("lint_regression")
    errs = []
    stop = threading.Event()

    def creator():
        i = 0
        fam = reg.counter("family")
        while not stop.is_set() and i < 30000:
            reg.counter(f"c{i}").inc()
            fam.labels(k=str(i)).inc()
            if i % 3 == 0:
                reg.histogram(f"h{i}").record(i)
            i += 1

    def scraper():
        try:
            while not stop.is_set():
                reg.snapshot()
                reg.prometheus_text()
        except RuntimeError as e:        # the pre-fix failure mode
            errs.append(e)

    threads = [threading.Thread(target=creator, daemon=True),
               threading.Thread(target=scraper, daemon=True),
               threading.Thread(target=scraper, daemon=True)]
    for t in threads:
        t.start()
    threads[0].join(timeout=30)
    stop.set()
    for t in threads[1:]:
        t.join(timeout=10)
    assert not errs, f"scrape raced metric creation: {errs[0]!r}"


def test_histogram_concurrent_record_is_exact():
    """FIXED by this PR: Histogram had no lock — concurrent record() lost
    count/total updates and the reservoir raced summary()'s numpy read.
    With the lock, count/total are exact under contention."""
    from paddle_tpu.observability.metrics import Histogram

    h = Histogram(max_samples=256)
    N, T = 20000, 4
    errs = []

    def writer():
        try:
            for i in range(N):
                h.record(1.0)
                if i % 500 == 0:
                    h.summary()
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    assert h.count == N * T
    assert h.total == float(N * T)
    assert h.summary()["count"] == N * T


def test_flight_recorder_concurrent_alarm_and_dump():
    """FIXED by this PR: FlightRecorder.__len__/alarm touched the ring and
    the frozen alarm snapshot without the lock."""
    from paddle_tpu.observability.serving_stall import FlightRecorder

    fr = FlightRecorder(max_steps=64)
    errs = []

    def stepper():
        try:
            for i in range(5000):
                fr.record_step(i=i)
                if i % 50 == 0:
                    fr.alarm("test", f"at {i}")
        except Exception as e:
            errs.append(e)

    def reader():
        try:
            for _ in range(2000):
                len(fr)
                fr.dump(last=8)
                _ = fr.last_alarm_dump
                _ = fr.steps_recorded
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=stepper),
               threading.Thread(target=reader),
               threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    assert fr.steps_recorded == 5000
    assert fr.last_alarm_dump is not None
    assert fr.last_alarm_dump["kind"] == "test"


def test_request_tracer_get_concurrent_with_finish():
    """FIXED by this PR: RequestTracer.get() read the live/done dicts
    without the lock while finish() rebalanced them."""
    from paddle_tpu.observability.request_trace import RequestTracer

    tr = RequestTracer(enabled=True, max_completed=32)
    errs = []

    def lifecycle():
        try:
            for i in range(4000):
                tr.start(i)
                tr.finish(i)
        except Exception as e:
            errs.append(e)

    def getter():
        try:
            for i in range(8000):
                tr.get(i % 4000)
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=lifecycle),
               threading.Thread(target=getter)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs


def test_annotations_are_runtime_inert():
    from paddle_tpu.observability.annotations import (
        guarded_by,
        holds_lock,
        hot_path,
    )

    @hot_path
    def f():
        return 41

    @hot_path(reason="why")
    def g():
        return 42

    @holds_lock("_lock")
    def h():
        return 43

    assert f() == 41 and g() == 42 and h() == 43
    assert f.__graft_hot_path__ is True
    assert g.__graft_hot_path__ == "why"
    assert h.__graft_holds_lock__ == "_lock"
    assert guarded_by("_lock").lock == "_lock"
    assert "guarded_by" in repr(guarded_by("_lock"))


def test_bench_json_canonicalization(tmp_path):
    """Satellite: bench artifacts write with sorted keys + stable floats,
    so a no-change re-run is a no-diff."""
    from tools.bench_io import canonical, write_bench_json

    art_a = {"b": 0.1 + 0.2, "a": [3.0, {"z": 1, "y": 2.0000000001}],
             "n": None, "t": True}
    art_b = {"t": True, "n": None,
             "a": [3, {"y": 2.0, "z": 1}], "b": 0.3}
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    write_bench_json(str(p1), art_a)
    write_bench_json(str(p2), art_b)
    assert p1.read_text() == p2.read_text()      # byte-identical
    assert canonical(float("nan")) == "nan"
    assert canonical(0.123456789) == 0.123457
    assert canonical(66.0) == 66
    assert json.loads(p1.read_text())["b"] == 0.3
