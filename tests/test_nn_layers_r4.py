"""r4 nn-layer closure tests: every newly added layer runs, the heavier
ones (unpool, adaptive log-softmax, RNNT, beam search) are checked
numerically (reference python/paddle/nn/layer/*)."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def _t(shape, seed=0):
    return paddle.to_tensor(np.random.default_rng(seed).normal(
        size=shape).astype(np.float32))


def test_pads_and_shapes():
    x = _t((2, 3, 8))
    assert nn.Pad1D([1, 2])(x).shape == [2, 3, 11]
    assert nn.ZeroPad1D([1, 1])(x).shape == [2, 3, 10]
    x2 = _t((2, 3, 4, 4))
    assert nn.ZeroPad2D(1)(x2).shape == [2, 3, 6, 6]
    x3 = _t((1, 2, 3, 4, 4))
    assert nn.Pad3D(1)(x3).shape == [1, 2, 5, 6, 6]
    assert nn.ZeroPad3D(1)(x3).shape == [1, 2, 5, 6, 6]
    assert nn.Unflatten(1, [3, 2])(_t((2, 6))).shape == [2, 3, 2]
    out = nn.Softmax2D()(x2)
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(1), 1.0,
                               rtol=1e-5)


def test_upsampling_and_instance_norms():
    x = _t((1, 2, 4, 4))
    assert nn.UpsamplingNearest2D(scale_factor=2)(x).shape == [1, 2, 8, 8]
    assert nn.UpsamplingBilinear2D(size=(6, 6))(x).shape == [1, 2, 6, 6]
    x1 = _t((2, 3, 16))
    out = nn.InstanceNorm1D(3)(x1).numpy()
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    x3 = _t((1, 2, 4, 4, 4))
    out3 = nn.InstanceNorm3D(2)(x3)
    assert out3.shape == [1, 2, 4, 4, 4]


def test_pool3d_family():
    x = _t((1, 2, 4, 8, 8))
    assert nn.MaxPool3D(2)(x).shape == [1, 2, 2, 4, 4]
    assert nn.AvgPool3D(2)(x).shape == [1, 2, 2, 4, 4]
    assert nn.AdaptiveAvgPool3D(2)(x).shape == [1, 2, 2, 2, 2]
    assert nn.AdaptiveMaxPool3D(2)(x).shape == [1, 2, 2, 2, 2]
    x1 = _t((1, 2, 8))
    assert nn.AdaptiveMaxPool1D(4)(x1).shape == [1, 2, 4]
    assert nn.LPPool1D(2, 2)(x1).shape == [1, 2, 4]
    assert nn.LPPool2D(2, 2)(_t((1, 2, 4, 4))).shape == [1, 2, 2, 2]
    assert nn.FractionalMaxPool2D(3)(_t((1, 2, 7, 7))).shape == [1, 2, 3, 3]
    assert nn.FractionalMaxPool3D(2)(
        _t((1, 1, 5, 5, 5))).shape == [1, 1, 2, 2, 2]


def test_max_unpool_round_trip():
    x = _t((2, 3, 8, 8))
    out, mask = F.max_pool_with_mask(x, 2, 2, 0, nd=2)
    rec = nn.MaxUnPool2D(2)(out, mask)
    assert rec.shape == [2, 3, 8, 8]
    rr = np.asarray(rec.numpy())
    oo = np.asarray(out.numpy())
    # the maxima land back at their argmax positions, zeros elsewhere
    np.testing.assert_allclose(np.sort(rr[rr != 0]), np.sort(oo.ravel()))
    # re-pooling the sparse reconstruction: zeros dominate negative maxima
    pooled_again = F.max_pool2d(rec, 2)
    np.testing.assert_allclose(np.asarray(pooled_again.numpy()),
                               np.maximum(oo, 0.0), rtol=1e-6)


def test_misc_layers():
    a, b = _t((4, 6), 1), _t((4, 6), 2)
    cs = nn.CosineSimilarity(axis=1)(a, b)
    assert cs.shape == [4]
    pd = nn.PairwiseDistance()(a, b)
    assert (np.asarray(pd.numpy()) >= 0).all()
    bl = nn.Bilinear(6, 6, 3)
    assert bl(a, b).shape == [4, 3]
    assert nn.ChannelShuffle(2)(_t((1, 4, 2, 2))).shape == [1, 4, 2, 2]
    assert nn.PixelUnshuffle(2)(_t((1, 1, 4, 4))).shape == [1, 4, 2, 2]
    d3 = nn.Dropout3D(0.5)
    d3.eval()
    x5 = _t((1, 2, 2, 2, 2))
    np.testing.assert_allclose(np.asarray(d3(x5).numpy()),
                               np.asarray(x5.numpy()))
    r = nn.RReLU()
    r.eval()
    out = np.asarray(r(paddle.to_tensor(
        np.asarray([-1.0, 2.0], np.float32))).numpy())
    np.testing.assert_allclose(out, [-(1 / 8 + 1 / 3) / 2, 2.0], rtol=1e-5)
    assert nn.Unfold(2)(_t((1, 2, 4, 4))).shape[1] == 8
    assert nn.Conv1DTranspose(3, 4, 3)(_t((1, 3, 8))).shape[1] == 4
    assert nn.Conv3DTranspose(2, 3, 2)(_t((1, 2, 3, 3, 3))).shape[1] == 3


def test_loss_layers():
    x = _t((4, 5), 3)
    y = paddle.to_tensor((np.arange(4) % 5).astype(np.int64))
    for loss in (nn.MultiMarginLoss(), nn.SoftMarginLoss(),
                 nn.GaussianNLLLoss()):
        pass
    assert float(nn.MultiMarginLoss()(x, y).numpy()) > 0
    yb = paddle.to_tensor(np.sign(np.random.default_rng(4).normal(
        size=(4, 5))).astype(np.float32))
    assert float(nn.SoftMarginLoss()(x, yb).numpy()) > 0
    ml = paddle.to_tensor((np.random.default_rng(5).random((4, 5)) > 0.5
                           ).astype(np.float32))
    assert float(nn.MultiLabelSoftMarginLoss()(x, ml).numpy()) > 0
    var = paddle.to_tensor(np.ones((4, 5), np.float32))
    assert np.isfinite(float(nn.GaussianNLLLoss()(x, _t((4, 5), 6),
                                                  var).numpy()))
    t = nn.TripletMarginWithDistanceLoss(margin=0.5)
    assert float(t(_t((3, 4), 7), _t((3, 4), 8), _t((3, 4), 9)).numpy()) >= 0
    p = nn.PoissonNLLLoss()
    assert np.isfinite(float(p(_t((3, 4), 10),
                               paddle.to_tensor(np.ones((3, 4),
                                                        np.float32))).numpy()))
    h = nn.HSigmoidLoss(8, 6)
    lbl = paddle.to_tensor((np.arange(4) % 6).astype(np.int64))
    out = h(_t((4, 8), 11), lbl)
    assert out.shape == [4, 1] and (np.asarray(out.numpy()) > 0).all()


def test_rnnt_loss_degenerate_equals_nll():
    """U=0 (empty label): the RNNT lattice is a pure blank path, so the
    loss is -sum_t log P(blank | t)."""
    rng = np.random.default_rng(0)
    B, T, V = 2, 4, 5
    logits = rng.normal(size=(B, T, 1, V)).astype(np.float32)
    x = paddle.to_tensor(logits)
    labels = paddle.to_tensor(np.zeros((B, 0), np.int32))
    il = paddle.to_tensor(np.full((B,), T, np.int32))
    ll = paddle.to_tensor(np.zeros((B,), np.int32))
    loss = float(F.rnnt_loss(x, labels, il, ll, reduction="mean").numpy())
    lp = np.asarray(jnp.log(jnp.exp(logits) / jnp.exp(logits).sum(
        -1, keepdims=True)))
    ref = -lp[:, :, 0, 0].sum(1).mean()
    np.testing.assert_allclose(loss, ref, rtol=1e-5)


def test_adaptive_log_softmax():
    paddle.seed(0)
    m = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[5, 10])
    x = _t((6, 16), 12)
    y = paddle.to_tensor(np.asarray([0, 4, 6, 9, 12, 19], np.int64))
    out, loss = m(x, y)
    assert out.shape == [6] and float(loss.numpy()) > 0
    lp = m.log_prob(x)
    assert lp.shape == [6, 20]
    np.testing.assert_allclose(np.exp(np.asarray(lp.numpy())).sum(1), 1.0,
                               rtol=1e-4)
    # the picked entries match the full log_prob table
    np.testing.assert_allclose(
        np.asarray(out.numpy()),
        np.take_along_axis(np.asarray(lp.numpy()),
                           np.asarray(y.numpy())[:, None], 1)[:, 0],
        rtol=1e-5)
    pred = m.predict(x)
    np.testing.assert_array_equal(
        np.asarray(pred.numpy()),
        np.argmax(np.asarray(lp.numpy()), axis=1))


def test_beam_search_decodes_argmax_sequence():
    """A cell whose logits are input-independent must decode the argmax
    token repeatedly; beam search recovers it as the top beam."""
    V, H = 7, 7

    class ConstCell(nn.RNNCellBase):
        hidden_size = H

        def __init__(self, logits):
            super().__init__()
            self._logits = paddle.to_tensor(logits)

        def forward(self, inputs, states):
            (h,) = states
            batch = inputs.shape[0]
            out = paddle.to_tensor(np.tile(
                np.asarray(self._logits.numpy())[None], (batch, 1)))
            return out, [h]

    logits = np.zeros((V,), np.float32)
    logits[3] = 4.0       # dominant token
    logits[0] = 2.0       # end token is second-best
    cell = ConstCell(logits)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=3)
    h0 = paddle.zeros((2, H))
    ids, scores = nn.dynamic_decode(dec, inits=[h0], max_step_num=4)
    assert ids.shape == [2, 3, 4]
    np.testing.assert_array_equal(np.asarray(ids.numpy())[:, 0, :], 3)
    s = np.asarray(scores.numpy())
    assert (s[:, 0] >= s[:, 1]).all() and (s[:, 1] >= s[:, 2]).all()


def test_adaptive_log_softmax_trains():
    """The loss must reach the head and tail weights (a detached forward
    would leave every grad None)."""
    import paddle_tpu.optimizer as opt

    paddle.seed(1)
    m = nn.AdaptiveLogSoftmaxWithLoss(8, 12, cutoffs=[4])
    o = opt.Adam(learning_rate=5e-2, parameters=m.parameters())
    x = _t((16, 8), 13)
    y = paddle.to_tensor((np.arange(16) % 12).astype(np.int64))
    first = last = None
    for _ in range(15):
        _, loss = m(x, y)
        loss.backward()
        o.step()
        o.clear_grad()
        v = float(loss.numpy())
        first = first if first is not None else v
        last = v
    assert last < first * 0.8, (first, last)


def test_hsigmoid_non_power_of_two_depth():
    """Labels at shallower leaves must NOT pick up a phantom decision
    against the last internal node (the masked-walk fix)."""
    paddle.seed(2)
    m = nn.HSigmoidLoss(4, 6)
    x = _t((1, 4), 14)
    # label 0 -> leaf code 6: exactly two decisions (6->3->1)
    out = float(m(x, paddle.to_tensor(np.asarray([0], np.int64))).numpy())
    w = np.asarray(m.weight.numpy())
    b = np.asarray(m.bias.numpy())
    xv = np.asarray(x.numpy())[0]

    def sig(z):
        return 1 / (1 + np.exp(-z))

    # walk: node 3-1=2 with bit0 of 6 (=0), node 1-1=0 with bit1 of 6 (=1)
    l2 = xv @ w[2] + b[2]
    l0 = xv @ w[0] + b[0]
    ref = -(np.log(1 - sig(l2)) + np.log(sig(l0)))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_adaptive_pools_non_divisible():
    x = _t((1, 2, 7))
    assert nn.AdaptiveMaxPool1D(3)(x).shape == [1, 2, 3]
    x3 = _t((1, 2, 5, 7, 9))
    assert nn.AdaptiveAvgPool3D(2)(x3).shape == [1, 2, 2, 2, 2]
    assert nn.AdaptiveMaxPool3D(3)(x3).shape == [1, 2, 3, 3, 3]
    with pytest.raises(NotImplementedError):
        F.fractional_max_pool2d(_t((1, 1, 4, 4)), 2, return_mask=True)
    with pytest.raises(NotImplementedError):
        F.rnnt_loss(_t((1, 2, 1, 3)), paddle.to_tensor(
            np.zeros((1, 0), np.int32)),
            paddle.to_tensor(np.asarray([2], np.int32)),
            paddle.to_tensor(np.asarray([0], np.int32)),
            fastemit_lambda=0.001)


def test_unflatten_negative_axis():
    assert nn.Unflatten(-1, [3, 2])(_t((2, 6))).shape == [2, 3, 2]
