"""Fused transformer LAYER classes (reference incubate/nn/layer/
fused_transformer.py): numeric equality of each fused layer against a
plain unfused composition built from the same parameters, plus a short
training drill through the encoder layer."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate import nn as inn

B, S, D, H, FF = 2, 8, 32, 4, 64
EPS = 1e-5


def _ln(h, s, b, eps=EPS):
    mu = h.mean(-1, keepdims=True)
    var = h.var(-1, keepdims=True)
    return (h - mu) / np.sqrt(var + eps) * s + b


def _x():
    return np.random.default_rng(0).normal(size=(B, S, D)).astype(np.float32)


def test_fused_mha_matches_unfused_postln():
    paddle.seed(1)
    layer = inn.FusedMultiHeadAttention(D, H, dropout_rate=0.0,
                                        attn_dropout_rate=0.0)
    layer.eval()
    x = _x()
    out = np.asarray(layer(paddle.to_tensor(x)).numpy())

    qkv_w = np.asarray(layer.qkv_weight.numpy())   # [3, H, hd, D]
    qkv_b = np.asarray(layer.qkv_bias.numpy())     # [3, H, hd]
    lin_w = np.asarray(layer.linear_weight.numpy())
    lin_b = np.asarray(layer.linear_bias.numpy())
    lns = np.asarray(layer.ln_scale.numpy())
    lnb = np.asarray(layer.ln_bias.numpy())

    hd = D // H
    qkv = x @ qkv_w.reshape(3 * H * hd, D).T + qkv_b.reshape(-1)
    qkv = qkv.reshape(B, S, 3, H, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    # [B, H, S, hd]
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    logits = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd)
    w = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    attn = (w @ v).transpose(0, 2, 1, 3).reshape(B, S, D)
    ref = _ln(x + (attn @ lin_w + lin_b), lns, lnb)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_fused_ffn_matches_unfused_preln():
    paddle.seed(2)
    layer = inn.FusedFeedForward(D, FF, dropout_rate=0.0,
                                 normalize_before=True)
    layer.eval()
    x = _x()
    out = np.asarray(layer(paddle.to_tensor(x)).numpy())

    w1 = np.asarray(layer.linear1_weight.numpy())
    b1 = np.asarray(layer.linear1_bias.numpy())
    w2 = np.asarray(layer.linear2_weight.numpy())
    b2 = np.asarray(layer.linear2_bias.numpy())
    s1 = np.asarray(layer._ln1_scale.numpy())
    lb1 = np.asarray(layer._ln1_bias.numpy())
    h = _ln(x, s1, lb1)
    ref = x + (np.maximum(h @ w1 + b1, 0.0) @ w2 + b2)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_fused_bias_dropout_residual_ln():
    paddle.seed(3)
    layer = inn.FusedBiasDropoutResidualLayerNorm(D, dropout_rate=0.0)
    layer.eval()
    x, r = _x(), _x() * 0.5
    out = np.asarray(layer(paddle.to_tensor(x),
                           paddle.to_tensor(r)).numpy())
    ref = _ln(r + x + np.asarray(layer.linear_bias.numpy()),
              np.asarray(layer.ln_scale.numpy()),
              np.asarray(layer.ln_bias.numpy()))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_encoder_layer_trains():
    paddle.seed(4)
    import paddle_tpu.optimizer as opt

    enc = inn.FusedTransformerEncoderLayer(D, H, FF, dropout_rate=0.0)
    head = nn.Linear(D, 2)
    params = enc.parameters() + head.parameters()
    o = opt.Adam(learning_rate=5e-3, parameters=params)
    ce = nn.CrossEntropyLoss()
    x = paddle.to_tensor(_x())
    y = paddle.to_tensor((np.arange(B) % 2).astype(np.int64))
    first = last = None
    for _ in range(8):
        pooled = enc(x).mean(axis=1)
        loss = ce(head(pooled), y)
        loss.backward()
        o.step()
        o.clear_grad()
        v = float(np.asarray(loss.numpy()))
        first = first if first is not None else v
        last = v
    assert last < first, (first, last)


def test_multi_transformer_stacks():
    paddle.seed(5)
    mt = inn.FusedMultiTransformer(D, H, FF, num_layers=3)
    mt.eval()
    out = mt(paddle.to_tensor(_x()))
    assert tuple(out.shape) == (B, S, D)
    with pytest.raises(NotImplementedError):
        mt(paddle.to_tensor(_x()), caches=[1])


def test_gelu_is_exact_and_bias_attr_false():
    from paddle_tpu.incubate.nn import functional as incubate_f

    # exact-erf gelu, not the tanh approximation
    h = jnp.asarray(np.linspace(-3, 3, 7, dtype=np.float32))
    out = incubate_f._act_raw(h, "gelu")
    exact = np.asarray(jax.nn.gelu(h, approximate=False))
    approx = np.asarray(jax.nn.gelu(h, approximate=True))
    np.testing.assert_allclose(np.asarray(out), exact, rtol=1e-6)
    assert not np.allclose(np.asarray(out), approx, rtol=1e-6, atol=0)

    # bias_attr=False drops the projection biases (paddle contract)
    layer = inn.FusedMultiHeadAttention(D, H, qkv_bias_attr=False,
                                        linear_bias_attr=False,
                                        dropout_rate=0.0,
                                        attn_dropout_rate=0.0)
    assert layer.qkv_bias is None and layer.linear_bias is None
    layer.eval()
    out = layer(paddle.to_tensor(_x()))
    assert np.isfinite(np.asarray(out.numpy())).all()


def test_bdrln_downscale_in_infer_mode():
    from paddle_tpu.incubate.nn import functional as incubate_f

    x, r = _x(), _x()
    # inference in downscale mode scales the non-residual term by (1-p)
    out = incubate_f.fused_bias_dropout_residual_layer_norm(
        paddle.to_tensor(x), paddle.to_tensor(r), dropout_rate=0.5,
        training=False, mode="downscale_in_infer")
    ref = _ln(r + 0.5 * x, np.ones(D, np.float32), np.zeros(D, np.float32))
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=2e-4,
                               atol=2e-5)
