"""Sparse (op, variant) parity audit + kernel tests (VERDICT r2 missing #2).

The reference's sparse_ops.yaml defines 51 sparse kernel variants
(/root/reference/paddle/phi/ops/yaml/sparse_ops.yaml); 30 of the names
collide with dense ops, so these are audited as SEPARATE (op, "sparse")
rows: every row is either implemented in paddle_tpu.sparse or a justified
skip, and the implementations are exercised against dense/numpy references
below (semantics: phi/kernels/sparse/ — unary ops touch stored values only,
softmax normalizes over stored entries per row).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.sparse as sp
from paddle_tpu.ops.parity import SPARSE_IMPLEMENTED, SPARSE_SKIPPED
from paddle_tpu.ops.ref_manifest import SPARSE_VARIANT_OPS


def _rand_coo(rng, shape=(6, 8), density=0.3, dtype=np.float32):
    dense = rng.normal(size=shape).astype(dtype)
    mask = rng.random(shape) < density
    dense = np.where(mask, dense, 0.0).astype(dtype)
    return sp.to_sparse_coo(paddle.to_tensor(dense)), dense


# ---------------------------------------------------------------------------
# audit: the 51-row partition is total, disjoint, and honest
# ---------------------------------------------------------------------------

def test_sparse_variant_partition_is_total_and_disjoint():
    names = set(SPARSE_VARIANT_OPS)
    impl, skip = set(SPARSE_IMPLEMENTED), set(SPARSE_SKIPPED)
    assert len(names) == 51
    assert impl | skip == names, sorted(names - (impl | skip))
    assert not (impl & skip)


def test_sparse_implemented_entries_resolve_and_are_sparse_aware():
    """Each claimed implementation must exist in paddle_tpu.sparse — the
    module whose ops understand COO/CSR inputs — not merely share a name
    with a dense op."""
    for ref_name, attr in SPARSE_IMPLEMENTED.items():
        fn = getattr(sp, attr, None)
        assert callable(fn), f"sparse {ref_name} -> sp.{attr} missing"


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def test_unary_ops_touch_stored_values_only(rng):
    x, dense = _rand_coo(rng)
    for name in ["relu", "sin", "tanh", "square", "expm1", "log1p", "abs"]:
        out = getattr(sp, name)(x)
        ref = getattr(np, {"relu": "maximum", "abs": "abs"}.get(name, name),
                      None)
        got = out.numpy()
        if name == "relu":
            expected = np.maximum(dense, 0)
        elif name == "log1p":
            # stored values only: implicit zeros stay 0 (log1p(0)=0 anyway)
            expected = np.where(dense != 0, np.log1p(dense), 0.0)
        else:
            expected = np.where(dense != 0, getattr(np, name)(dense), 0.0)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
        assert out.is_sparse_coo()


def test_acos_keeps_implicit_zeros():
    """acos(0) = pi/2 but sparse acos must leave implicit zeros implicit —
    the defining difference from the dense kernel."""
    x = sp.sparse_coo_tensor([[0], [0]], [0.5], shape=[2, 2])
    out = sp.acos(x).numpy()
    assert out[0, 0] == pytest.approx(np.arccos(0.5))
    assert out[1, 1] == 0.0  # NOT pi/2


def test_leaky_relu_pow_scale_cast(rng):
    x, dense = _rand_coo(rng)
    np.testing.assert_allclose(
        sp.leaky_relu(x, 0.1).numpy(),
        np.where(dense >= 0, dense, 0.1 * dense), rtol=1e-5)
    np.testing.assert_allclose(
        sp.pow(x, 3).numpy(), dense ** 3, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        sp.scale(x, 2.0, 1.0).numpy(),
        np.where(dense != 0, dense * 2 + 1, 0.0), rtol=1e-5)
    c = sp.cast(x, value_dtype="float16")
    assert c.values().numpy().dtype == np.float16


def test_binary_add_subtract_align_index_sets(rng):
    x, dx = _rand_coo(rng)
    y, dy = _rand_coo(rng)
    np.testing.assert_allclose(sp.add(x, y).numpy(), dx + dy, rtol=1e-5)
    np.testing.assert_allclose(sp.subtract(x, y).numpy(), dx - dy, rtol=1e-5)
    np.testing.assert_allclose(sp.multiply(x, y).numpy(), dx * dy, rtol=1e-5)
    np.testing.assert_allclose(
        sp.divide_scalar(x, 2.0).numpy(), dx / 2.0, rtol=1e-5)


def test_matmul_mv_addmm(rng):
    x, dx = _rand_coo(rng, (5, 7))
    d = paddle.to_tensor(rng.normal(size=(7, 4)).astype(np.float32))
    np.testing.assert_allclose(
        sp.matmul(x, d).numpy(), dx @ d.numpy(), rtol=1e-4, atol=1e-5)
    v = paddle.to_tensor(rng.normal(size=(7,)).astype(np.float32))
    np.testing.assert_allclose(
        sp.mv(x, v).numpy(), dx @ v.numpy(), rtol=1e-4, atol=1e-5)
    inp = paddle.to_tensor(rng.normal(size=(5, 4)).astype(np.float32))
    np.testing.assert_allclose(
        sp.addmm(inp, x, d, beta=0.5, alpha=2.0).numpy(),
        0.5 * inp.numpy() + 2.0 * (dx @ d.numpy()), rtol=1e-4, atol=1e-5)


def test_masked_matmul_sddmm(rng):
    a = paddle.to_tensor(rng.normal(size=(5, 6)).astype(np.float32))
    b = paddle.to_tensor(rng.normal(size=(6, 5)).astype(np.float32))
    mask, dmask = _rand_coo(rng, (5, 5), density=0.4)
    out = sp.masked_matmul(a, b, mask)
    full = a.numpy() @ b.numpy()
    expected = np.where(dmask != 0, full, 0.0)
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-4, atol=1e-5)
    assert out.is_sparse_coo()


def test_softmax_over_stored_entries(rng):
    x, dense = _rand_coo(rng, (4, 6), density=0.5)
    out = sp.softmax(x).numpy()
    for r in range(4):
        nz = dense[r] != 0
        if nz.sum() == 0:
            continue
        e = np.exp(dense[r][nz] - dense[r][nz].max())
        np.testing.assert_allclose(out[r][nz], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(out[r][~nz], 0.0)


def test_sum_reduction(rng):
    x, dense = _rand_coo(rng)
    np.testing.assert_allclose(
        float(sp.sum(x).numpy()), dense.sum(), rtol=1e-5)
    np.testing.assert_allclose(
        sp.sum(x, axis=1).to_dense().numpy(), dense.sum(1), rtol=1e-5)


def test_reshape_transpose_slice(rng):
    x, dense = _rand_coo(rng, (4, 6))
    np.testing.assert_allclose(
        sp.reshape(x, [3, 8]).numpy(), dense.reshape(3, 8))
    np.testing.assert_allclose(
        sp.transpose(x, [1, 0]).numpy(), dense.T)
    np.testing.assert_allclose(
        sp.slice(x, [0, 1], [1, 2], [3, 5]).numpy(), dense[1:3, 2:5])


def test_coalesce_sums_duplicates():
    x = sp.sparse_coo_tensor([[0, 0, 1], [1, 1, 0]], [1.0, 2.0, 3.0],
                             shape=[2, 2])
    c = sp.coalesce(x)
    assert c.numpy()[0, 1] == pytest.approx(3.0)


def test_mask_as_and_full_like(rng):
    x = paddle.to_tensor(rng.normal(size=(4, 4)).astype(np.float32))
    mask, dmask = _rand_coo(rng, (4, 4), density=0.4)
    got = sp.mask_as(x, mask).numpy()
    np.testing.assert_allclose(got, np.where(dmask != 0, x.numpy(), 0.0),
                               rtol=1e-6)
    fl = sp.full_like(mask, 7.0)
    np.testing.assert_allclose(fl.numpy(), np.where(dmask != 0, 7.0, 0.0))


def test_csr_roundtrip_and_formats(rng):
    x, dense = _rand_coo(rng, (5, 7))
    csr = sp.to_sparse_csr(x)
    assert csr.is_sparse_csr() and not csr.is_sparse_coo()
    np.testing.assert_allclose(csr.to_dense().numpy(), dense)
    # crows is a valid monotone rowptr ending at nnz
    crows = csr.crows().numpy()
    assert crows[0] == 0 and crows[-1] == csr.nnz()
    assert (np.diff(crows) >= 0).all()
    back = csr.to_sparse_coo()
    np.testing.assert_allclose(back.to_dense().numpy(), dense)
    # CSR ctor parity
    csr2 = sp.sparse_csr_tensor(crows, csr.cols().numpy(),
                                csr.values().numpy(), [5, 7])
    np.testing.assert_allclose(csr2.to_dense().numpy(), dense)
    # unary on CSR stays CSR
    r = sp.relu(csr)
    assert r.is_sparse_csr()
    np.testing.assert_allclose(r.to_dense().numpy(), np.maximum(dense, 0))


def test_sparse_batch_norm(rng):
    # NDHWC-flattened: shape [N*D*H*W, C] with channels as last index col
    C = 4
    x, dense = _rand_coo(rng, (20, C), density=0.5)
    bn = sp.nn.BatchNorm(C, momentum=0.9)
    out = bn(x)
    got = out.numpy()
    # reference semantics: per-channel stats over STORED values
    for c in range(C):
        nz = dense[:, c] != 0
        if nz.sum() < 2:
            continue
        v = dense[:, c][nz]
        mean, var = v.mean(), v.var()
        expected = (v - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(got[:, c][nz], expected, rtol=1e-3,
                                   atol=1e-4)
    assert out.is_sparse_coo()


def test_sparse_fused_attention(rng):
    B, H, S, D = 1, 2, 4, 8
    q = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    k = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    v = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    # causal pattern as the sparse mask
    tri = np.tril(np.ones((S, S), np.float32))
    mask = sp.to_sparse_coo(paddle.to_tensor(tri))
    out = sp.fused_attention(q, k, v, mask).numpy()
    # dense reference
    logits = (q.numpy() @ np.swapaxes(k.numpy(), -1, -2)) / np.sqrt(D)
    logits = np.where(tri != 0, logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, p @ v.numpy(), rtol=1e-4, atol=1e-5)


def test_divide_same_pattern_no_nan_densification():
    """divide(sparse, sparse) must not turn implicit zeros into stored
    NaNs (0/0 at every empty position) — review r3 finding."""
    x = sp.sparse_coo_tensor([[0], [0]], [2.0], shape=[2, 2])
    out = sp.divide(x, x)
    got = out.numpy()
    assert got[0, 0] == pytest.approx(1.0)
    assert not np.isnan(got).any()
    assert out.nnz() <= 2  # no NaN densification


def test_fused_attention_key_padding_mask(rng):
    B, H, S, D = 1, 1, 4, 8
    q = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    k = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    v = paddle.to_tensor(rng.normal(size=(B, H, S, D)).astype(np.float32))
    full = sp.to_sparse_coo(paddle.to_tensor(np.ones((S, S), np.float32)))
    kp = paddle.to_tensor(np.asarray([[1, 1, 0, 0]], np.float32))
    out = sp.fused_attention(q, k, v, full, key_padding_mask=kp).numpy()
    # reference: softmax over the first two keys only
    logits = (q.numpy() @ np.swapaxes(k.numpy(), -1, -2)) / np.sqrt(D)
    logits[..., 2:] = -np.inf
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out, p @ v.numpy(), rtol=1e-4, atol=1e-5)
