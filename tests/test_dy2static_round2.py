"""Round-2 dy2static tests (VERDICT #5): for-loops over tensors,
break/continue via early-exit flags, both-branch returns, and the minimal
SOT tier (guards + graph-break fallback). Pattern: the reference's
test/sot/test_01_basic.py / test/dygraph_to_static — run the same function
eager vs captured and assert equality.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static
from paddle_tpu.jit.sot import sot_stats, symbolic_translate


def t(v, dtype=None):
    return paddle.to_tensor(np.asarray(v), dtype=dtype)


def check_same(fn, *args, n=None):
    eager = fn(*args)
    static = to_static(fn)(*args)
    np.testing.assert_allclose(np.asarray(static.numpy()),
                               np.asarray(eager.numpy()), rtol=1e-6)
    return static


# --------------------------------------------------------------- for loops


def test_for_range_tensor_bound():
    def fn(n, x):
        s = x
        for i in range(n):
            s = s + i
        return s

    check_same(fn, t(5), t(0.0))
    # eager python-int path still exact
    check_same(fn, 4, t(1.0))


def test_for_range_start_stop_step():
    def fn(n, x):
        s = x
        for i in range(2, n, 3):
            s = s + i
        return s

    check_same(fn, t(11), t(0.0))  # 2 + 5 + 8 = 15


def test_for_over_tensor_rows():
    def fn(m):
        s = paddle.zeros([3])
        for row in m:
            s = s + row
        return s

    m = t(np.arange(12, dtype=np.float32).reshape(4, 3))
    check_same(fn, m)


def test_for_over_python_list():
    def fn(x):
        s = x
        for v in [1.0, 2.0, 3.0]:
            s = s * v
        return s

    check_same(fn, t(2.0))


def test_nested_for_if():
    def fn(n, x):
        s = x
        for i in range(n):
            if s > 10.0:
                s = s - 1.0
            else:
                s = s + i
        return s

    check_same(fn, t(8), t(0.0))


# --------------------------------------------------------- break / continue


def test_while_with_break():
    def fn(x):
        i = 0
        s = x
        while i < 100:
            s = s + 1.0
            if s > 5.0:
                break
            i = i + 1
        return s

    check_same(fn, t(0.0))


def test_for_with_break():
    def fn(n, x):
        s = x
        for i in range(n):
            if i >= 3:
                break
            s = s + 10.0
        return s

    check_same(fn, t(100), t(0.0))  # only 3 iterations accumulate


def test_for_with_continue():
    def fn(n, x):
        s = x
        for i in range(n):
            if i % 2 == 0:
                continue
            s = s + i
        return s

    check_same(fn, t(6), t(0.0))  # 1 + 3 + 5 = 9


def test_for_break_and_continue():
    def fn(n, x):
        s = x
        for i in range(n):
            if i % 2 == 0:
                continue
            if i > 5:
                break
            s = s + i
        return s

    check_same(fn, t(100), t(0.0))  # 1 + 3 + 5 = 9


# ------------------------------------------------------------------ return


def test_if_both_branches_return():
    def fn(x):
        if x > 0:
            return x * 2.0
        else:
            return -x

    check_same(fn, t(3.0))
    check_same(fn, t(-4.0))


def test_return_in_loop_falls_back_to_eager():
    # unsupported subset: stays eager but still CORRECT through to_static
    def fn(n, x):
        for i in range(int(n)):
            if i == 2:
                return x + 100.0
        return x

    out = to_static(fn, full_graph=False)(3, t(1.0))
    assert float(out.numpy()) == 101.0


# ------------------------------------------------ review-repro regressions


def test_both_return_branch_reassigns_local():
    def fn(flag, x):
        if flag:
            x = x + 1.0
            return x
        else:
            return x

    check_same(fn, t(True), t(2.0))
    check_same(fn, t(False), t(2.0))
    # python predicate path too
    assert float(to_static(fn)(True, t(2.0)).numpy()) == 3.0


def test_temp_after_conditional_break():
    def fn(n, x):
        s = x
        i = 0
        while i < n:
            if s > 100.0:
                break
            tmp = s * 2.0
            s = tmp + 1.0
            i = i + 1
        return s

    check_same(fn, t(5), t(1.0))


def test_break_does_not_reevaluate_unsafe_test():
    vals = [1.0, 2.0, 3.0]

    def fn():
        i = 0
        while vals[i] < 10.0:
            i = i + 1
            if i >= 3:
                break
        return paddle.to_tensor(float(i))

    # eager python path: vals[3] must NOT be evaluated after break
    assert float(to_static(fn)().numpy()) == 3.0


def test_for_over_generator_stays_lazy():
    seen = []

    def gen():
        for i in range(10):
            seen.append(i)
            yield float(i)

    def fn(x):
        s = x
        for v in gen():
            if v > 2.0:
                break
            s = s + v
        return s

    out = to_static(fn)(t(0.0))
    assert float(out.numpy()) == 3.0  # 0 + 1 + 2
    assert len(seen) == 4  # generator NOT drained past the break


def test_break_inside_try_falls_back_eager():
    def fn(n, x):
        i = 0
        while i < int(n):
            try:
                if i == 2:
                    break
            finally:
                pass
            x = x + 1.0
            i = i + 1
        return x

    out = to_static(fn, full_graph=False)(5, t(0.0))
    assert float(out.numpy()) == 2.0


def test_return_in_nested_loop_orelse_not_transformed():
    def fn(n, x):
        i = 0
        while i < int(n):
            j = 0
            while j < 2:
                j = j + 1
            else:
                return x + 100.0
            i = i + 1
        return x

    out = to_static(fn, full_graph=False)(3, t(1.0))
    assert float(out.numpy()) == 101.0


# ----------------------------------------------------------------- SOT tier


def test_sot_guard_specializations():
    def fn(x, k):
        return x * k

    wrapped = symbolic_translate(fn)
    a = wrapped(t(2.0), 3)
    assert float(a.numpy()) == 6.0
    wrapped(t(5.0), 3)        # same guards -> same specialization
    wrapped(t([1.0, 2.0]), 3)  # new shape -> new specialization
    wrapped(t(2.0), 4)         # new python arg value -> new specialization
    stats = sot_stats(wrapped)
    assert stats["specializations"] == 3
    assert not stats["fallback"]


def test_sot_closure_value_guard():
    k = 3

    def fn(x):
        return x * k

    wrapped = symbolic_translate(fn)
    assert float(wrapped(t(2.0)).numpy()) == 6.0
    k = 5  # closure cell changes -> guard miss -> fresh capture
    assert float(wrapped(t(2.0)).numpy()) == 10.0
    assert sot_stats(wrapped)["specializations"] == 2


def test_sot_graph_break_is_handled_by_bytecode_tier():
    def fn(x):
        # .numpy() on a traced value feeding python control flow: in round 2
        # this meant permanent eager fallback; the bytecode tier now handles
        # it as a sub-function graph break (tests/test_sot_bytecode.py has
        # the full matrix)
        if float(x.numpy()) > 0:
            return x + 1.0
        return x - 1.0

    wrapped = symbolic_translate(fn)
    out = wrapped(t(2.0))
    assert float(out.numpy()) == 3.0
    out2 = wrapped(t(-2.0))
    assert float(out2.numpy()) == -3.0
    stats = sot_stats(wrapped)
    assert not stats["fallback"]          # NOT permanently eager anymore
    assert stats["bytecode"]
    assert stats["bytecode_breaks"] >= 2  # one break per call
