"""AMP fp16 parity (VERDICT r3 weak #3): per-dtype white/black lists,
OD level, promote toggle, and the fp16 dynamic-loss-scaling drill where
the inf comes from FP16 RANGE (not an artificial 1e38 input) — force an
overflow, assert skip + scale halving, then recovery with scale growth.
Reference: python/paddle/amp/amp_lists.py:30-108, grad_scaler.py:619."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_per_dtype_white_lists_differ():
    from paddle_tpu.amp import amp_lists

    w16 = amp_lists.white_list("float16")
    wbf = amp_lists.white_list("bfloat16")
    assert amp_lists.ONLY_FP16_WHITE_LIST <= w16
    assert not (amp_lists.ONLY_FP16_WHITE_LIST & wbf)
    # common MXU core present in both
    assert {"matmul", "conv2d", "einsum"} <= (w16 & wbf)
    # extra-black (lossy grads) ops are black for both dtypes
    assert "embedding" in amp_lists.black_list("float16")
    assert "embedding" in amp_lists.black_list("bfloat16")


def test_fp16_autocast_white_and_black():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="float16"):
        y = paddle.matmul(x, x)
        s = F.softmax(x)
    assert y.dtype == paddle.float16
    assert s.dtype == paddle.float32


def test_od_level_everything_else_fp32():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    h = paddle.to_tensor(np.ones((4, 4), np.float16))
    with paddle.amp.auto_cast(level="OD", dtype="float16"):
        y = paddle.matmul(x, x)          # white: fp16
        r = paddle.nn.functional.relu(h)  # unlisted: fp32 at OD
    assert y.dtype == paddle.float16
    assert r.dtype == paddle.float32


def test_promote_toggle():
    lo = paddle.to_tensor(np.ones((4,), np.float16))
    hi = paddle.to_tensor(np.ones((4,), np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="float16"):
        mixed = lo + hi
    assert mixed.dtype == paddle.float32  # promote on (default)
    with paddle.amp.auto_cast(level="O1", dtype="float16",
                              use_promote=False):
        followed = lo + hi  # unlisted, mixed: follow the LOW side
        kept = paddle.nn.functional.relu(lo)
    assert followed.dtype == paddle.float16
    assert kept.dtype == paddle.float16


def test_bad_level_raises():
    with pytest.raises(ValueError):
        with paddle.amp.auto_cast(level="O7"):
            pass


def test_fp16_o2_gradscaler_drill(rng):
    """The GradScaler's reason to exist: fp16 O2 training where the scale
    itself overflows fp16 grads. Step 1 at scale 2^16 on O(1) grads
    overflows (inf) -> update skipped, scale halves; subsequent steps at
    the reduced scale succeed and the scale doubles back after
    incr_every_n_steps good steps."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.api import TrainStep

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    optimizer = opt.SGD(learning_rate=1e-3,
                        parameters=model.parameters())
    model, optimizer = paddle.amp.decorate(model, optimizer, level="O2",
                                           dtype="float16")
    assert model[0].weight.dtype == paddle.float16

    # fp16 max is 65504: scale 2^17 x grads O(1) overflows in the scaled
    # backward; after ONE halving (2^16) grads ~ 6.5e4 * 0.5 fit
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 17,
                                   decr_every_n_nan_or_inf=1,
                                   incr_every_n_steps=2)
    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        with paddle.amp.auto_cast(level="O2", dtype="float16"):
            return mse(m(x), y)

    step = TrainStep(model, loss_fn, optimizer, scaler=scaler)
    x = paddle.to_tensor(
        rng.standard_normal((8, 8)).astype(np.float16))
    y = paddle.to_tensor(np.ones((8, 1), np.float16))

    w0 = np.asarray(model[0].weight.numpy(), np.float32).copy()
    step(x, y)
    # overflow: update skipped, scale halved
    np.testing.assert_allclose(
        np.asarray(model[0].weight.numpy(), np.float32), w0)
    assert scaler.get_loss_scaling() == 2.0 ** 16

    # the scale keeps halving while grads still overflow fp16, then
    # training proceeds and good steps grow it back (the hunt)
    scales, losses = [], []
    for _ in range(6):
        losses.append(float(step(x, y).numpy()))
        scales.append(scaler.get_loss_scaling())
    assert not np.allclose(
        np.asarray(model[0].weight.numpy(), np.float32), w0)
    assert losses[-1] < losses[0]
    assert min(scales) < 2.0 ** 16          # halved further while inf
    # recovery: after the scale bottoms out, good steps grow it again
    first_min = scales.index(min(scales))
    assert any(s > min(scales) for s in scales[first_min + 1:]), scales
