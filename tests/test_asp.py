"""ASP n:m sparsity mask simulation (reference: incubate/asp/asp.py;
test model test/asp/test_asp_pruning_*.py — masks hold through training)."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate import asp


def test_mask_1d_is_2_of_4():
    paddle.seed(0)
    net = nn.Linear(16, 8)
    masks = asp.prune_model(net, n=2, m=4, mask_algo="mask_1d")
    assert masks
    w = np.asarray(net.weight.numpy())
    groups = w.reshape(-1, 4)
    nz = (groups != 0).sum(axis=1)
    assert (nz <= 2).all()
    assert abs(asp.calculate_density(net.weight) - 0.5) < 0.1


def test_mask_2d_greedy_rowcol_constraint():
    paddle.seed(1)
    net = nn.Linear(8, 8)
    asp.prune_model(net, n=2, m=4, mask_algo="mask_2d_greedy")
    w = np.asarray(net.weight.numpy()).reshape(8, 8)
    for i0 in range(0, 8, 4):
        for j0 in range(0, 8, 4):
            blk = w[i0:i0 + 4, j0:j0 + 4]
            assert ((blk != 0).sum(axis=0) <= 2).all()
            assert ((blk != 0).sum(axis=1) <= 2).all()


def test_masks_hold_through_training():
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optimizer = asp.decorate(opt.Adam(learning_rate=1e-2,
                                      parameters=net.parameters()))
    asp.prune_model(net, n=2, m=4)
    zero_masks = {
        id(p): np.asarray(p.numpy()) == 0
        for p in net.parameters() if len(p.shape) >= 2
    }
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    mse = nn.MSELoss()
    for _ in range(4):
        loss = mse(net(x), y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
    for p in net.parameters():
        if id(p) in zero_masks:
            w = np.asarray(p.numpy())
            assert (w[zero_masks[id(p)]] == 0).all()
            # non-masked entries actually trained
            assert (w[~zero_masks[id(p)]] != 0).any()


def test_excluded_layers():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
    asp.set_excluded_layers(net, ["0"])
    asp.prune_model(net, n=2, m=4)
    w0 = np.asarray(net[0].weight.numpy())
    w1 = np.asarray(net[1].weight.numpy())
    assert (w0 != 0).all()  # excluded: untouched
    assert (w1 == 0).any()
    asp.reset_excluded_layers(net)


def test_masks_hold_through_hapi_fast_path():
    """The hapi compiled TrainStep bypasses optimizer.step(); the ASP
    post-step hook must still re-apply masks."""
    paddle.seed(4)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optimizer = asp.decorate(opt.Adam(learning_rate=1e-2,
                                      parameters=net.parameters()))
    asp.prune_model(net, n=2, m=4)
    zeros = {id(p): np.asarray(p.numpy()) == 0
             for p in net.parameters() if len(p.shape) >= 2}
    m = paddle.Model(net)
    m.prepare(optimizer, nn.MSELoss())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    for _ in range(4):
        m.train_batch([x], [y])
    for p in net.parameters():
        if id(p) in zeros:
            assert (np.asarray(p.numpy())[zeros[id(p)]] == 0).all()


def test_masks_hold_through_trainstep():
    """jit.TrainStep bypasses the wrapper's step(); the post-step hook must
    still re-mask."""
    from paddle_tpu.jit.api import TrainStep

    paddle.seed(5)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    optimizer = asp.decorate(opt.Adam(learning_rate=1e-2,
                                      parameters=net.parameters()))
    asp.prune_model(net, n=2, m=4)
    zeros = {id(p): np.asarray(p.numpy()) == 0
             for p in net.parameters() if len(p.shape) >= 2}
    mse = nn.MSELoss()
    step = TrainStep(net, lambda m, a, b: mse(m(a), b), optimizer)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    for _ in range(3):
        step(x, y)
    for p in net.parameters():
        if id(p) in zeros:
            assert (np.asarray(p.numpy())[zeros[id(p)]] == 0).all()
    assert optimizer._step_count == 3  # setattr forwards to inner
    assert optimizer._inner._step_count == 3
