"""Tensor semantics tests (reference: test/legacy_test/test_eager_tensor.py
style — numpy-reference checks)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_dtypes():
    t = paddle.to_tensor([1.0, 2.0, 3.0])
    assert t.dtype == np.float32
    assert t.shape == [3]
    i = paddle.to_tensor([1, 2, 3])
    assert i.dtype == np.int64 or i.dtype == np.int32
    np.testing.assert_allclose(t.numpy(), [1.0, 2.0, 3.0])


def test_python_float64_downcast():
    t = paddle.to_tensor(3.14)
    assert t.dtype == np.float32


def test_basic_arithmetic():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4.0, 6.0])
    np.testing.assert_allclose((a - b).numpy(), [-2.0, -2.0])
    np.testing.assert_allclose((a * b).numpy(), [3.0, 8.0])
    np.testing.assert_allclose((a / b).numpy(), [1 / 3, 0.5], rtol=1e-6)
    np.testing.assert_allclose((a ** 2).numpy(), [1.0, 4.0])
    np.testing.assert_allclose((-a).numpy(), [-1.0, -2.0])
    np.testing.assert_allclose((3.0 + a).numpy(), [4.0, 5.0])
    np.testing.assert_allclose((3.0 - a).numpy(), [2.0, 1.0])
    np.testing.assert_allclose((6.0 / b).numpy(), [2.0, 1.5])


def test_comparison_ops():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    np.testing.assert_array_equal((a >= b).numpy(), [False, True, True])


def test_getitem_setitem():
    t = paddle.to_tensor(np.arange(12.0).reshape(3, 4))
    np.testing.assert_allclose(t[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(t[:, 2].numpy(), [2, 6, 10])
    np.testing.assert_allclose(t[1:, ::2].numpy(), [[4, 6], [8, 10]])
    t[0] = 0.0
    np.testing.assert_allclose(t[0].numpy(), [0, 0, 0, 0])
    t[2, 3] = 99.0
    assert t.numpy()[2, 3] == 99.0


def test_astype_item_len_iter():
    t = paddle.to_tensor([1.5, 2.5])
    assert t.astype("int32").numpy().dtype == np.int32
    assert len(t) == 2
    assert paddle.to_tensor(7.0).item() == 7.0
    vals = [float(x) for x in t]
    assert vals == [1.5, 2.5]


def test_clone_detach():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    c = t.clone()
    d = t.detach()
    assert d.stop_gradient
    np.testing.assert_allclose(c.numpy(), t.numpy())


def test_shape_size_ndim():
    t = paddle.to_tensor(np.zeros((2, 3, 4)))
    assert t.shape == [2, 3, 4]
    assert t.ndim == 3
    assert t.size == 24
    assert t.numel() == 24


def test_creation_ops():
    np.testing.assert_allclose(paddle.zeros([2, 2]).numpy(), np.zeros((2, 2)))
    np.testing.assert_allclose(paddle.ones([2]).numpy(), [1, 1])
    np.testing.assert_allclose(paddle.full([2], 5.0).numpy(), [5, 5])
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    np.testing.assert_allclose(
        paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
    )
    e = paddle.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3))


def test_manipulation_ops():
    x = paddle.to_tensor(np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(
        paddle.reshape(x, [3, 2]).numpy(), np.arange(6.0).reshape(3, 2)
    )
    np.testing.assert_allclose(
        paddle.transpose(x, [1, 0]).numpy(), x.numpy().T
    )
    np.testing.assert_allclose(
        paddle.concat([x, x], axis=0).numpy(), np.concatenate([x.numpy()] * 2, 0)
    )
    np.testing.assert_allclose(
        paddle.stack([x, x], axis=0).numpy(), np.stack([x.numpy()] * 2, 0)
    )
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[1].numpy(), x.numpy()[:, 1:2])
    np.testing.assert_allclose(
        paddle.squeeze(paddle.unsqueeze(x, 0), 0).numpy(), x.numpy()
    )


def test_reduction_ops():
    x = np.arange(6.0).reshape(2, 3)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.sum(t).numpy(), x.sum())
    np.testing.assert_allclose(paddle.mean(t, axis=0).numpy(), x.mean(0))
    np.testing.assert_allclose(paddle.max(t, axis=1).numpy(), x.max(1))
    np.testing.assert_allclose(paddle.min(t).numpy(), x.min())
    np.testing.assert_allclose(paddle.prod(t, axis=1).numpy(), x.prod(1))
    assert paddle.argmax(t).item() == 5


def test_linalg():
    a = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    b = np.random.default_rng(1).normal(size=(4, 5)).astype(np.float32)
    out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5, atol=1e-5)


def test_math_unary():
    x = np.array([0.5, 1.0, 2.0], dtype=np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.exp(t).numpy(), np.exp(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.log(t).numpy(), np.log(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.sqrt(t).numpy(), np.sqrt(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.tanh(t).numpy(), np.tanh(x), rtol=1e-6)
    np.testing.assert_allclose(paddle.abs(paddle.to_tensor(-x)).numpy(), x)


def test_inplace_add_():
    t = paddle.to_tensor([1.0, 2.0])
    if hasattr(t, "add_"):
        t.add_(paddle.to_tensor([1.0, 1.0]))
        np.testing.assert_allclose(t.numpy(), [2.0, 3.0])


def test_copy_set_value():
    t = paddle.to_tensor([1.0, 2.0])
    t.set_value(np.array([9.0, 9.0], dtype=np.float32))
    np.testing.assert_allclose(t.numpy(), [9.0, 9.0])


def test_string_tensor_basic():
    """StringTensor parity (phi/core/string_tensor.h + strings kernels):
    host-resident string tensor with lower/upper and the int boundary."""
    from paddle_tpu.framework import StringTensor, to_string_tensor

    st = to_string_tensor([["Hello", "World"], ["TPU", "Paddle"]])
    assert st.shape == [2, 2]
    assert st.dtype == "pstring"
    assert st.numel() == 4
    low = st.lower()
    assert low.tolist() == [["hello", "world"], ["tpu", "paddle"]]
    up = st.upper()
    assert up.tolist() == [["HELLO", "WORLD"], ["TPU", "PADDLE"]]
    # original untouched (functional kernels)
    assert st.tolist()[0][0] == "Hello"
    assert st[0][1] == "World"
    # bytes decode + non-ascii utf8 length
    st2 = StringTensor([b"abc", "é"])
    bl = st2.byte_length()
    assert bl.numpy().tolist() == [3, 2]
