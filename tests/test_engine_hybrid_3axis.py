"""Engine-driven dp x mp x pp in ONE program (VERDICT r3 missing #2).

The reference's static Engine parallelizes data, tensor and pipeline axes
inside one distributed program (auto_parallel/static/engine.py:68 +
parallelizer_v2.py). Here: GPT on a 2x2x2 virtual mesh through
Engine.fit / dist.to_static — embedding, megatron-TP decoder stack inside
the 1F1B schedule engine, tied head, and AdamW all in one jitted step —
with LOSS EQUALITY against the plain dygraph TrainStep."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
from paddle_tpu.models.gpt import gpt_tiny

import jax


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

B, S, STEPS = 8, 32, 3
LR, WD = 1e-3, 0.01


def _data():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1024, (B, S)).astype(np.int32)
    return ids


def _dygraph_losses(model, ids_np):
    from paddle_tpu.jit.api import TrainStep

    criterion = GPTPretrainingCriterion(model.config)
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())

    def loss_fn(m, ids, labels):
        return criterion(m(ids), labels)

    step = TrainStep(model, loss_fn, optimizer)
    ids = paddle.to_tensor(ids_np)
    return [float(step(ids, ids).numpy()) for _ in range(STEPS)]


def test_hybrid_step_loss_equality_2x2x2():
    """HybridTrainStep directly: 3 training steps on pp=2 x mp=2 x dp=2
    match the dygraph trajectory."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep

    paddle.framework.random.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    ids_np = _data()

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    step = HybridTrainStep(model, mesh, optimizer, pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=2)
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    # the hybrid step never mutated the eager params: the dygraph reference
    # starts from the identical init
    dygraph = _dygraph_losses(model, ids_np)
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)


def test_engine_fit_3axis_mesh():
    """Engine.fit over a 3-axis ProcessMesh routes through HybridTrainStep
    and reproduces the dygraph loss history; sync_model writes trained
    weights back for eval."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    from paddle_tpu.distributed.auto_parallel.static_engine import Engine

    paddle.framework.random.seed(1)
    model = GPTForCausalLM(gpt_tiny())
    ids_np = _data()
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["pp", "mp", "dp"])

    criterion = GPTPretrainingCriterion(model.config)
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    loader = [(paddle.to_tensor(ids_np), paddle.to_tensor(ids_np))
              for _ in range(STEPS)]
    eng = Engine(model, loss=criterion, optimizer=optimizer, mesh=mesh,
                 pp_axis="pp", tp_axis="mp", num_microbatches=2)
    history = eng.fit(loader, epochs=1)
    assert len(history) == STEPS
    assert history[-1] < history[0]

    # same-init equality: rebuild with the same seed and compare
    paddle.framework.random.seed(1)
    model2 = GPTForCausalLM(gpt_tiny())
    dygraph2 = _dygraph_losses(model2, ids_np)
    np.testing.assert_allclose(history, dygraph2, rtol=2e-4, atol=1e-5)

    # eval path: dm syncs weights back into the eager model
    dm = eng._dist_model
    dm.eval()
    out = dm(paddle.to_tensor(ids_np), paddle.to_tensor(ids_np))
    assert np.isfinite(float(out.numpy()))


def test_llama_hybrid_step_loss_equality_2x2x2():
    """LLaMA (RMSNorm + RoPE + GQA + SwiGLU, untied head) through the SAME
    one-program dp x mp x pp route: BASELINE.md config #5's auto_parallel
    path, second model family through the Engine tier."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models.llama import (
        LlamaForCausalLM,
        LlamaPretrainingCriterion,
        llama_tiny,
    )

    paddle.framework.random.seed(3)
    model = LlamaForCausalLM(llama_tiny())
    ids_np = _data()

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    step = HybridTrainStep(model, mesh, optimizer, pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=2)
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    criterion = LlamaPretrainingCriterion(model.config)
    optimizer2 = opt.AdamW(learning_rate=LR, weight_decay=WD,
                           parameters=model.parameters())

    def loss_fn(m, ids, labels):
        return criterion(m(ids), labels)

    dstep = TrainStep(model, loss_fn, optimizer2)
    ids = paddle.to_tensor(ids_np)
    dygraph = [float(dstep(ids, ids).numpy()) for _ in range(STEPS)]
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)

    # sync_model writes the trained stacked weights back into the eager
    # model (untied head + RMSNorm included)
    step.sync_model()
    out = model(paddle.to_tensor(ids_np[:2]))
    assert np.isfinite(np.asarray(out.numpy())).all()


def test_hybrid_step_grad_clip_and_decay_fun():
    """ClipGradByGlobalNorm + apply_decay_param_fun on the hybrid route
    reproduce the dygraph trajectory (the r4 close of the 'raise loudly'
    gap). clip_norm is small enough that the clip is ACTIVE every step."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep
    from paddle_tpu.nn import ClipGradByGlobalNorm

    paddle.framework.random.seed(2)
    model = GPTForCausalLM(gpt_tiny())
    ids_np = _data()
    # decay matmul/embedding weights only (the standard no-bias-no-ln
    # filter) — keyed on auto-generated param names, uniform per layer
    decay_names = {p.name for p in model.parameters() if p.ndim > 1}

    def mk_opt():
        return opt.AdamW(learning_rate=LR, weight_decay=0.1,
                         parameters=model.parameters(),
                         grad_clip=ClipGradByGlobalNorm(0.05),
                         apply_decay_param_fun=lambda n: n in decay_names)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    step = HybridTrainStep(model, mesh, mk_opt(), pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=2)
    # the decay filter resolved per logical leaf: weights decay, biases/ln
    # do not
    assert step._wd_s["qkv_w"] == 0.1 and step._wd_s["qkv_b"] == 0.0
    assert step._wd_e["word"] == 0.1 and step._wd_h["lnf_b"] == 0.0
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    from paddle_tpu.jit.api import TrainStep

    criterion = GPTPretrainingCriterion(model.config)

    def loss_fn(m, ids, labels):
        return criterion(m(ids), labels)

    dstep = TrainStep(model, loss_fn, mk_opt())
    ids = paddle.to_tensor(ids_np)
    dygraph = [float(dstep(ids, ids).numpy()) for _ in range(STEPS)]
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)


def test_zbv_hybrid_step_loss_equality_2x2x2():
    """policy="ZBV": the zero-bubble V schedule (two chunks per device)
    drives the SAME one-program dp x mp x pp route — loss trajectory and
    synced-back weights match dygraph. Closes the 'ZB-V not wired into
    HybridTrainStep' r4 gap."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep

    paddle.framework.random.seed(3)
    # 4 layers: ZB-V needs num_layers % (2*pp) == 0 (one early + one late
    # chunk per device)
    model = GPTForCausalLM(gpt_tiny(num_layers=4))
    ids_np = _data()

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    step = HybridTrainStep(model, mesh, optimizer, pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=4,
                           policy="ZBV")
    assert step._zbv and step.schedule.num_microbatches == 4
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    dygraph = _dygraph_losses(model, ids_np)
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)

    # sync_model restores LAYER order through zbv_unpermute before
    # write_back: the synced eager model must score like the dygraph model
    # at the same point in training (after STEPS steps)
    step.sync_model()
    criterion = GPTPretrainingCriterion(model.config)
    ids = paddle.to_tensor(ids_np)
    synced = float(criterion(model(ids), ids).numpy())

    paddle.framework.random.seed(3)
    model2 = GPTForCausalLM(gpt_tiny(num_layers=4))
    _dygraph_losses(model2, ids_np)  # trains model2 in place for STEPS
    synced_dy = float(criterion(model2(ids), ids).numpy())
    np.testing.assert_allclose(synced, synced_dy, rtol=2e-4, atol=1e-5)


def test_engine_fit_zbv_schedule_mode():
    """Engine honors DistributedStrategy.pipeline_configs["schedule_mode"]
    (reference: pipeline_scheduler_pass naming): "ZBV" routes the hybrid
    step through the V schedule, reproducing the dygraph loss history."""
    from paddle_tpu.distributed.auto_parallel import ProcessMesh
    from paddle_tpu.distributed.auto_parallel.static_engine import Engine
    from paddle_tpu.distributed.fleet.fleet import DistributedStrategy

    paddle.framework.random.seed(4)
    model = GPTForCausalLM(gpt_tiny(num_layers=4))
    ids_np = _data()
    mesh = ProcessMesh(np.arange(8).reshape(2, 2, 2),
                       dim_names=["pp", "mp", "dp"])
    strategy = DistributedStrategy()
    strategy.pipeline_configs = {"schedule_mode": "ZBV"}

    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    loader = [(paddle.to_tensor(ids_np), paddle.to_tensor(ids_np))
              for _ in range(STEPS)]
    eng = Engine(model, optimizer=optimizer, mesh=mesh, strategy=strategy,
                 pp_axis="pp", tp_axis="mp", num_microbatches=4)
    history = eng.fit(loader, epochs=1)
    assert eng._dist_model._step._zbv
    assert len(history) == STEPS

    paddle.framework.random.seed(4)
    model2 = GPTForCausalLM(gpt_tiny(num_layers=4))
    dygraph = _dygraph_losses(model2, ids_np)
    np.testing.assert_allclose(history, dygraph, rtol=2e-4, atol=1e-5)


def test_hybrid_step_custom_loss_equality():
    """A label-smoothed CE — inexpressible by the fused head — routes
    through the dense-logits custom head and reproduces the dygraph
    trajectory (r4: closes the 'custom losses raise loudly' gap)."""
    import paddle_tpu.nn.functional as F
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep
    from paddle_tpu.jit.api import TrainStep

    paddle.framework.random.seed(5)
    model = GPTForCausalLM(gpt_tiny())
    ids_np = _data()
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())

    # ONE callable under the dygraph criterion contract (paddle Tensors
    # in, scalar Tensor out) serves both the engine and the dygraph path
    def smooth_ce(logits, labels):
        v = logits.shape[-1]
        return F.cross_entropy(logits.reshape((-1, v)),
                               labels.reshape((-1,)),
                               label_smoothing=0.1)

    step = HybridTrainStep(model, mesh, optimizer, pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=2,
                           loss_fn=smooth_ce)
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    criterion_opt = opt.AdamW(learning_rate=LR, weight_decay=WD,
                              parameters=model.parameters())

    def dy_loss(m, ids, labels):
        return smooth_ce(m(ids), labels)

    dstep = TrainStep(model, dy_loss, criterion_opt)
    ids = paddle.to_tensor(ids_np)
    dygraph = [float(dstep(ids, ids).numpy()) for _ in range(STEPS)]
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)


def test_llama_zbv_hybrid_step_loss_equality():
    """LLaMA (GQA + SwiGLU + untied head) on the ZB-V schedule: the second
    model family through the V-placement engine, equality vs dygraph."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.auto_parallel.hybrid import HybridTrainStep
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models.llama import (
        LlamaForCausalLM,
        LlamaPretrainingCriterion,
        llama_tiny,
    )

    paddle.framework.random.seed(6)
    model = LlamaForCausalLM(llama_tiny(num_layers=4))
    ids_np = _data()

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("pp", "mp", "dp"))
    optimizer = opt.AdamW(learning_rate=LR, weight_decay=WD,
                          parameters=model.parameters())
    step = HybridTrainStep(model, mesh, optimizer, pp_axis="pp",
                           mp_axis="mp", dp_axis="dp", num_microbatches=4,
                           policy="ZBV")
    assert step._zbv
    hybrid = [float(step(ids_np, ids_np).numpy()) for _ in range(STEPS)]

    criterion = LlamaPretrainingCriterion(model.config)
    optimizer2 = opt.AdamW(learning_rate=LR, weight_decay=WD,
                           parameters=model.parameters())
    dstep = TrainStep(model, lambda m, i, t: criterion(m(i), t), optimizer2)
    ids = paddle.to_tensor(ids_np)
    dygraph = [float(dstep(ids, ids).numpy()) for _ in range(STEPS)]
    np.testing.assert_allclose(hybrid, dygraph, rtol=2e-4, atol=1e-5)
