"""Quantization pipeline tests (VERDICT #9): QAT insert/convert and the PTQ
calibration loop (reference flow: python/paddle/quantization/{qat,ptq}.py).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.quantization import (
    QAT,
    PTQ,
    AbsmaxObserver,
    FakeQuanterWithAbsMaxObserver,
    QuantConfig,
    QuantedLayer,
    QuantizedInferenceLayer,
    collect_scales,
)
from paddle_tpu.vision.models.lenet import LeNet


def _mnistish_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, (n,)).astype(np.int64)
    return X, y


def test_qat_insert_swaps_layers():
    model = LeNet()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                      weight=FakeQuanterWithAbsMaxObserver)
    q = QAT(cfg)
    qmodel = q.quantize(model)
    wrapped = [l for l in qmodel.sublayers() if isinstance(l, QuantedLayer)]
    assert len(wrapped) >= 3  # convs + linears got wrapped


def test_qat_lenet_trains_close_to_fp32():
    X, y = _mnistish_data()
    lossfn = nn.CrossEntropyLoss()

    def train(quantize):
        paddle.framework.random.seed(123)
        model = LeNet()
        if quantize:
            cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                              weight=FakeQuanterWithAbsMaxObserver)
            model = QAT(cfg).quantize(model)
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
        losses = []
        for _ in range(6):
            loss = lossfn(model(paddle.to_tensor(X)), paddle.to_tensor(y))
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        return model, losses

    fp_model, fp_losses = train(False)
    q_model, q_losses = train(True)
    # QAT tracks the fp32 trajectory within tolerance (STE + int8 sim)
    assert q_losses[-1] < q_losses[0]
    assert abs(q_losses[-1] - fp_losses[-1]) < 0.35 * max(fp_losses[-1], 0.5)


def test_qat_convert_produces_int8_weights():
    import jax.numpy as jnp

    X, y = _mnistish_data(16)
    paddle.framework.random.seed(1)
    model = LeNet()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                      weight=FakeQuanterWithAbsMaxObserver)
    q = QAT(cfg)
    qmodel = q.quantize(model)
    # a few forwards so EMA scales exist
    for _ in range(3):
        qmodel(paddle.to_tensor(X))
    ref_out = qmodel(paddle.to_tensor(X)).numpy()

    converted = q.convert(qmodel)
    infl = [l for l in converted.sublayers()
            if isinstance(l, QuantizedInferenceLayer)]
    assert infl
    for l in infl:
        assert l.qweight is not None
        assert l.qweight.dtype == jnp.int8
        assert l.w_scale and l.w_scale > 0
    out = converted(paddle.to_tensor(X)).numpy()
    # converted int8 sim stays close to the observed-QAT forward
    assert np.mean(np.abs(out - ref_out)) < 0.25 * (np.abs(ref_out).mean() + 1e-3)


def test_ptq_calibration_produces_scales_and_converts():
    X, _ = _mnistish_data(32, seed=3)
    paddle.framework.random.seed(7)
    model = LeNet()
    fp_out = model(paddle.to_tensor(X)).numpy()

    cfg = QuantConfig(activation=AbsmaxObserver, weight=AbsmaxObserver)
    ptq = PTQ(cfg)
    observed = ptq.quantize(model)

    batches = [[paddle.to_tensor(X[i:i + 8])] for i in range(0, 32, 8)]
    n = ptq.calibrate(observed, batches)
    assert n == 4

    scales = collect_scales(observed)
    assert scales  # every wrapped layer calibrated
    for entry in scales.values():
        for v in entry.values():
            assert v is not None and v > 0

    converted = ptq.convert(observed)
    out = converted(paddle.to_tensor(X)).numpy()
    # int8 PTQ stays near the fp32 outputs on calibration data
    denom = np.abs(fp_out).mean() + 1e-6
    assert np.mean(np.abs(out - fp_out)) / denom < 0.2
    assert np.mean(np.argmax(out, -1) == np.argmax(fp_out, -1)) > 0.8


def test_hist_observer_robust_to_outliers():
    """NOTES_r2 gap: histogram calibration — one extreme outlier must not
    blow up the scale the way absmax does."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.quantization import AbsmaxObserver, HistObserver

    rng = np.random.default_rng(0)
    data = rng.normal(size=(4096,)).astype(np.float32)
    data[0] = 1000.0  # outlier
    t = paddle.to_tensor(data)
    absmax = AbsmaxObserver()
    hist = HistObserver(percent=0.999)
    absmax(t)
    hist(t)
    assert absmax.scales() > 5.0          # ruined by the outlier
    assert hist.scales() < 0.1            # percentile clips it


def test_kl_observer_reasonable_threshold():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.quantization import KLObserver

    rng = np.random.default_rng(1)
    data = rng.normal(size=(8192,)).astype(np.float32)
    t = paddle.to_tensor(data)
    obs = KLObserver()
    obs(t)
    # int8 scale for a unit gaussian should land near |x|max/127 ~ 0.03,
    # and the KL threshold must be within the observed range
    s = obs.scales()
    assert 0.005 < s < 0.05, s


def test_hist_observer_rebins_on_range_expansion():
    """Review r3: when a later batch widens the range, the accumulated
    histogram must re-bin to the new range (not pile old mass into the top
    bin, which would blow up the percentile threshold)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.quantization import HistObserver

    rng = np.random.default_rng(2)
    obs = HistObserver(percent=0.99)
    small = rng.uniform(0, 0.1, 8192).astype(np.float32)
    obs(paddle.to_tensor(small))
    s1 = obs.scales()
    # second batch doubles the range; the bulk of mass is still <= 0.1
    obs(paddle.to_tensor(np.concatenate(
        [small, np.asarray([0.2], np.float32)])))
    s2 = obs.scales()
    # correct re-binning keeps the 99% threshold near 0.1, NOT near 0.2
    assert s2 < 1.5 * s1, (s1, s2)


def test_kl_observer_rebins_on_range_expansion():
    """Advisor r3 (medium): KLObserver must re-bin accumulated counts when
    a later batch widens _hist_max — otherwise old counts binned under the
    narrow range are reinterpreted on the wider one, skewing the KL scale.

    Oracle: feeding batches incrementally must give (nearly) the same
    scale as feeding the concatenated data to a fresh observer."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.quantization import KLObserver

    rng = np.random.default_rng(3)
    a = rng.normal(0, 0.05, 8192).astype(np.float32)
    b = rng.normal(0, 1.0, 8192).astype(np.float32)  # 20x wider range

    inc = KLObserver()
    inc(paddle.to_tensor(a))
    inc(paddle.to_tensor(b))

    oracle = KLObserver()
    oracle(paddle.to_tensor(np.concatenate([a, b])))

    # rebinning preserves where the mass sits; without it the narrow
    # batch's counts land on wrong bins and shift the KL threshold
    assert abs(inc.scales() - oracle.scales()) < 0.25 * oracle.scales(), \
        (inc.scales(), oracle.scales())
