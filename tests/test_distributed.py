"""Distributed tests on the 8-device virtual CPU mesh (reference pattern:
test/collective/* semantics tests run multi-process on one host; here the
single-controller encoding runs all "ranks" as mesh devices)."""

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.distributed as dist


requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices"
)


@requires_8
def test_world_env():
    dist.init_parallel_env()
    assert dist.get_world_size() >= 1


@requires_8
def test_shard_tensor_and_reshard():
    mesh = dist.ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    t = paddle.to_tensor(np.arange(32.0, dtype=np.float32).reshape(8, 4))
    st = dist.shard_tensor(t, mesh, [dist.Shard(0), dist.Replicate()])
    np.testing.assert_allclose(st.numpy(), t.numpy())
    rs = dist.reshard(st, mesh, [dist.Replicate(), dist.Shard(1)])
    np.testing.assert_allclose(rs.numpy(), t.numpy())


@requires_8
def test_shard_layer():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["x"])
    layer = nn.Linear(8, 8)

    def shard_fn(name, sublayer, m):
        if hasattr(sublayer, "weight") and sublayer.weight is not None:
            sublayer.weight = dist.shard_tensor(
                sublayer.weight, m, [dist.Shard(1)]
            )

    sharded = dist.shard_layer(layer, mesh, shard_fn)
    x = paddle.to_tensor(np.ones((2, 8), dtype=np.float32))
    out = sharded(x)
    assert out.shape == [2, 8]
