"""Sharded multi-chip serving (paddle_tpu/serving/sharded/).

The contract under test: one serving replica spanning a tp mesh must be
OBSERVATIONALLY IDENTICAL to the single-device engine — token streams
bit-identical to the unsharded oracle at every dispatch_depth, through
forced preemption, prefix-cache eviction, and router kill-drill failover
— while the KV pool's bytes actually split ~1/tp per chip (pinned
against the per-device ledger census) and the one-compiled-decode-
program / zero-steady-state-recompile invariant holds at any tp.

Runs on the emulated CPU mesh (conftest forces
--xla_force_host_platform_device_count=8), so tp=2 and 2x-tp=2 router
fleets all fit. Every scheduler builds a FRESH identically-seeded model:
sharding COMMITS the model's parameters to its replica's mesh, so a
model object must never be shared across differently-placed schedulers.
"""

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (
    ContinuousBatchingScheduler,
    SchedulerConfig,
    ServingRouter,
)
from paddle_tpu.serving.sharded import (
    DeviceGroupPlan,
    TensorParallelSharding,
)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """XLA:CPU AOT replay corrupts decode-program numerics (see
    test_serving_async.py) — serving tests compile fresh."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _sched(depth=0, tp=None, plan="exact", **over):
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=8,
              dispatch_depth=depth)
    kw.update(over)
    sharding = TensorParallelSharding(tp=tp, plan=plan) if tp else None
    return ContinuousBatchingScheduler(_model(), SchedulerConfig(**kw),
                                       sharding=sharding)


def _prompts(n, seed=0, lo=4, hi=13):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 1000, int(k)) for k in rng.integers(lo, hi, n)]


def _pool_clean(sched):
    if sched.prefix_cache is not None:
        sched.prefix_cache.flush()
    assert sched.allocator.num_used_blocks == 0, (
        f"block leak: {sched.allocator.num_used_blocks} still held")


# ------------------------------------------------------- identity oracle

def test_sharded_matches_unsharded_oracle_every_depth():
    """tp in {1, 2} x dispatch_depth in {0, 2}: token streams bit-
    identical to the single-device engine AND the per-request eager
    greedy decode."""
    prompts = _prompts(4)
    oracle = _sched()
    refs = oracle.generate(prompts, max_new_tokens=5)
    oracle.shutdown()
    eager_model = _model()
    for p, ref in zip(prompts, refs):
        eag = eager_model.generate(
            paddle.to_tensor(p[None, :].astype(np.int64)),
            max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(eag.numpy())[0], ref)
    for tp in (1, 2):
        for depth in (0, 2):
            sched = _sched(depth=depth, tp=tp)
            outs = sched.generate(prompts, max_new_tokens=5)
            for o, ref in zip(outs, refs):
                np.testing.assert_array_equal(o, ref)
            sched.shutdown()
            _pool_clean(sched)


def test_sharded_preemption_resume_identical():
    """Pool sized so sequences preempt: the recompute-resume cycle on a
    head-sharded pool must not change a token vs the unsharded engine."""
    prompts = _prompts(2, seed=1, lo=9, hi=11)
    ref = None
    for tp in (None, 2):
        for depth in (0, 2):
            sched = _sched(depth=depth, tp=tp, block_size=4, num_blocks=6)
            outs = sched.generate(prompts, max_new_tokens=8)
            assert sched.metrics.snapshot()["preemptions"] >= 1
            if ref is None:
                ref = outs
            else:
                for a, b in zip(ref, outs):
                    np.testing.assert_array_equal(a, b)
            sched.shutdown()
            _pool_clean(sched)


def test_sharded_prefix_cache_eviction_identical():
    """Prefix caching + continuous LRU eviction over the sharded pool
    (COW block copies are eager ops on head-sharded arrays): identical
    streams with the cache on and off, at tp 1 and 2."""
    prompts = _prompts(6, seed=3, lo=9, hi=20)
    ref = None
    for tp in (None, 1, 2):
        sched = _sched(tp=tp, enable_prefix_caching=True, num_blocks=8)
        outs = sched.generate(prompts, max_new_tokens=5)
        assert sched.prefix_cache_stats()["evicted_blocks"] > 0
        if ref is None:
            ref = outs
        else:
            for a, b in zip(ref, outs):
                np.testing.assert_array_equal(a, b)
        sched.shutdown()
        _pool_clean(sched)
    plain = _sched(tp=2)
    outs = plain.generate(prompts, max_new_tokens=5)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)
    plain.shutdown()
    _pool_clean(plain)


# ------------------------------------------------- compiled-program pins

def test_zero_steady_state_recompiles_sharded():
    """The tentpole invariant survives the mesh: after mark_steady a
    second workload through the tp=2 engine compiles NOTHING, at sync
    and dispatch-ahead depths."""
    for depth in (0, 2):
        sched = _sched(depth=depth, tp=2, max_num_seqs=3)
        sched.generate(_prompts(4, seed=7), max_new_tokens=4)
        stats = sched.compile_stats()
        assert stats["compiles"] == sched.num_programs()
        sched.mark_steady()
        sched.generate(_prompts(5, seed=8), max_new_tokens=4)
        stats = sched.compile_stats()
        assert stats["steady_state_recompiles"] == 0
        sched.shutdown()
        _pool_clean(sched)


def test_bad_sharding_configs_rejected():
    import jax

    with pytest.raises(ValueError, match="plan"):
        TensorParallelSharding(tp=2, plan="nope")
    with pytest.raises(ValueError, match="num_heads"):
        _sched(tp=3)  # gpt_tiny has 4 heads; 4 % 3 != 0
    with pytest.raises(ValueError, match="devices"):
        TensorParallelSharding(tp=len(jax.devices()) + 1)
    with pytest.raises(ValueError):
        DeviceGroupPlan(tp=len(jax.devices()), replicas=2)


# --------------------------------------------------- per-device accounting

def test_per_device_ledger_census_matches_ground_truth():
    """The sharded KV split is falsifiable: per-chip census within 5% of
    bytes computed from the arrays' actual shardings, KV ~1/tp per chip,
    and the {owner,device} gauge series exported."""
    from paddle_tpu.observability.device_memory import (
        tree_device_nbytes,
        tree_nbytes,
    )

    sched = _sched(tp=2)
    rep = sched.device_ledger.census_report()
    kv = rep["owners"]["kv_pool"]
    pool_total = tree_nbytes(sched._pools)
    truth = tree_device_nbytes(sched._pools)
    assert set(kv["devices"]) == set(truth)
    assert len(truth) == 2
    for dev, nb in truth.items():
        # exact halves from the head shard
        assert nb * 2 == pool_total
        assert kv["devices"][dev] == nb
    # whole-replica per-chip census >= 95% of ground truth (weights+pool)
    w_truth = tree_device_nbytes([p for p in sched.model.parameters()])
    for dev in truth:
        ground = truth[dev] + w_truth[dev]
        assert rep["per_device"][dev] >= 0.95 * ground
    snap = sched.metrics.registry.snapshot()
    for dev in truth:
        key = (f'serving_device_memory_bytes{{device="{dev}",'
               f'owner="kv_pool"}}')
        assert snap[key] == truth[dev]
    sched.shutdown()
    _pool_clean(sched)


def test_device_observability_carries_per_chip_memory():
    sched = _sched(tp=2)
    obs = sched.device_observability(analyze=False)
    assert obs["enabled"]
    per_dev = obs["memory"]["per_device"]
    assert len(per_dev) == 2
    assert all(v > 0 for v in per_dev.values())
    sched.shutdown()
    _pool_clean(sched)


# ------------------------------------------------- router: disjoint fleets

def _make_replica(sh):
    return ContinuousBatchingScheduler(
        _model(), SchedulerConfig(max_num_seqs=2, max_seq_len=64,
                                  block_size=8),
        sharding=sh)


def test_router_kill_drill_sharded_survivors():
    """Kill a tp=2 replica mid-decode: every request completes on the
    OTHER tp=2 replica (disjoint chips) bit-identical to the single-
    device oracle, and the restarted replica comes back on its own
    device group."""
    prompts = _prompts(6, seed=4)
    oracle = _sched()
    orids = [oracle.add_request(p, max_new_tokens=6) for p in prompts]
    guard = 3000
    while oracle.has_unfinished():
        oracle.step()
        guard -= 1
        assert guard > 0
    refs = [oracle._finished[r].token_ids for r in orids]
    oracle.shutdown()

    plan = DeviceGroupPlan(tp=2, replicas=2)
    router = ServingRouter(plan.replica_factories(_make_replica),
                           cooldown_s=0.05, device_ownership="error")
    groups = [frozenset(rep.sched.device_set()) for rep in router.replicas]
    assert not groups[0] & groups[1]
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):
        router.step()
    router.crash_replica(0)
    outs = {}
    guard = 3000
    while len(outs) < len(rids):
        for o in router.step():
            outs[o.request_id] = o
        guard -= 1
        assert guard > 0
    for rid, ref in zip(rids, refs):
        np.testing.assert_array_equal(outs[rid].token_ids, ref)
    assert router.replicas[0].generation == 1
    # the restart went through replica 0's own factory -> same chips
    assert frozenset(router.replicas[0].sched.device_set()) == groups[0]
    router.shutdown()


def test_router_device_ownership_validation():
    """Overlapping replica device sets: error mode rejects, warn mode
    warns once per process, disjoint fleets stay silent."""
    import paddle_tpu.serving.router.router as router_mod

    def colocated():
        return ContinuousBatchingScheduler(
            _model(), SchedulerConfig(max_num_seqs=2, max_seq_len=64,
                                      block_size=8))

    with pytest.raises(ValueError, match="share devices"):
        ServingRouter(colocated, num_replicas=2, device_ownership="error")
    old = router_mod._OWNERSHIP_WARNED
    router_mod._OWNERSHIP_WARNED = False
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            r1 = ServingRouter(colocated, num_replicas=2)
            r2 = ServingRouter(colocated, num_replicas=2)
        hits = [w for w in caught if issubclass(w.category, RuntimeWarning)
                and "share devices" in str(w.message)]
        assert len(hits) == 1  # once per process, not per router
        r1.shutdown()
        r2.shutdown()
    finally:
        router_mod._OWNERSHIP_WARNED = old
    # disjoint sharded fleet passes the strict gate silently
    plan = DeviceGroupPlan(tp=1, replicas=2)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        router = ServingRouter(plan.replica_factories(_make_replica),
                               device_ownership="error")
    router.shutdown()


def test_router_factory_sequence_validation():
    def f():
        return None

    with pytest.raises(ValueError, match="factories"):
        ServingRouter([f, f, f], num_replicas=4)
    with pytest.raises(ValueError, match="callable"):
        ServingRouter([])
