"""Pins for the four ADVICE r4 findings: ASGD d/y accumulators,
soft_margin_loss overflow, static dynamic-dim double probe, p_norm forward
epsilon bias."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_asgd_matches_manual_sag():
    """ASGD must implement the reference recurrence (optimizer/asgd.py:36):
    d <- d - y_i + g; y_i <- g; x <- x - lr * d / min(m+1, n)."""
    import paddle_tpu.optimizer as opt

    n = 3
    lr = 0.1
    w0 = np.array([1.0, -2.0], np.float32)
    p = paddle.to_tensor(w0.copy())
    p.stop_gradient = False
    p.trainable = True
    o = opt.ASGD(learning_rate=lr, batch_num=n, parameters=[p])

    grads = [np.array(g, np.float32) for g in
             ([0.5, 1.0], [-1.0, 2.0], [2.0, -1.0], [0.25, 0.5],
              [1.0, 1.0])]
    # manual reference
    x = w0.copy()
    d = np.zeros(2, np.float32)
    ys = np.zeros((n, 2), np.float32)
    for m, g in enumerate(grads):
        i = m % n
        d = d - ys[i] + g
        ys[i] = g
        x = x - lr * d / min(m + 1, n)

    for g in grads:
        p._grad = paddle.to_tensor(g)._value
        o.step()
    np.testing.assert_allclose(np.asarray(p.numpy()), x, rtol=1e-5)


def test_asgd_batch_num_1_is_sgd():
    import paddle_tpu.optimizer as opt

    p = paddle.to_tensor(np.array([1.0], np.float32))
    p.stop_gradient = False
    p.trainable = True
    o = opt.ASGD(learning_rate=0.5, batch_num=1, parameters=[p])
    p._grad = paddle.to_tensor(np.array([2.0], np.float32))._value
    o.step()
    np.testing.assert_allclose(np.asarray(p.numpy()), [0.0], atol=1e-6)


def test_soft_margin_loss_large_logits_finite():
    x = paddle.to_tensor(np.array([200.0, -200.0], np.float32))
    y = paddle.to_tensor(np.array([-1.0, 1.0], np.float32))
    out = F.soft_margin_loss(x, y, reduction="none")
    v = np.asarray(out.numpy())
    assert np.isfinite(v).all()
    np.testing.assert_allclose(v, [200.0, 200.0], rtol=1e-5)
    # well-classified side ~ 0
    out2 = F.soft_margin_loss(x, paddle.to_tensor(
        np.array([1.0, -1.0], np.float32)), reduction="mean")
    assert float(np.asarray(out2.numpy())) < 1e-5


def test_p_norm_zero_vector_unbiased_with_finite_grad():
    z = paddle.to_tensor(np.zeros(4, np.float32))
    z.stop_gradient = False
    out = paddle.norm(z, p=2)
    assert float(np.asarray(out.numpy())) == 0.0  # was eps^(1/p) = 1e-3
    out.backward()
    assert np.isfinite(np.asarray(z.grad.numpy())).all()
    # nonzero vector: exact value, exact grad
    x = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    x.stop_gradient = False
    nrm = paddle.norm(x, p=2)
    np.testing.assert_allclose(float(np.asarray(nrm.numpy())), 5.0,
                               rtol=1e-6)
    nrm.backward()
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), [0.6, 0.8],
                               rtol=1e-4)


def test_static_keepdim_dim_not_mislabeled_dynamic():
    """A genuinely size-1 leading output dim must keep size 1 in the
    recorded Variable shape even when an input has a dynamic (-1) leading
    dim (ADVICE r4: single-probe collision)."""
    import paddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 8], "float32")
        # keepdim reduction over dim 0: output leading dim is ALWAYS 1
        red = paddle.sum(x, axis=0, keepdim=True)
        # plain batchwise op: leading dim tracks the batch -> stays -1
        y = paddle.relu(x)
    assert red.shape[0] == 1, red.shape
    assert y.shape[0] == -1, y.shape
