"""Double backward (create_graph=True) through the dygraph tape.

Reference semantics: paddle.grad(..., create_graph=True) returns gradients
that are themselves differentiable (python/paddle/autograd — double-grad
tests test/legacy_test/test_imperative_double_grad.py). Oracles are closed
forms.
"""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_second_derivative_cube():
    # d/dx x^3 = 3x^2 ; d2/dx2 = 6x
    x = paddle.to_tensor(np.array([2.0, -3.0], np.float32), stop_gradient=False)
    y = (x * x * x).sum()
    (gx,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 3 * np.array([4.0, 9.0]), rtol=1e-6)
    assert not gx.stop_gradient
    (ggx,) = paddle.grad(gx.sum(), [x])
    np.testing.assert_allclose(ggx.numpy(), 6 * np.array([2.0, -3.0]), rtol=1e-6)


def test_second_derivative_through_chain():
    # y = tanh(x); d2y/dx2 = -2 tanh(x) (1 - tanh(x)^2)
    xv = np.array([0.3, -0.7, 1.1], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = paddle.tanh(x).sum()
    (gx,) = paddle.grad(y, [x], create_graph=True)
    (ggx,) = paddle.grad(gx.sum(), [x])
    t = np.tanh(xv)
    np.testing.assert_allclose(ggx.numpy(), -2 * t * (1 - t * t), rtol=1e-5)


def test_mixed_partial():
    # f = x^2 * y ; df/dx = 2xy ; d/dy(df/dx) = 2x
    x = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
    y = paddle.to_tensor(np.float32(5.0), stop_gradient=False)
    f = x * x * y
    (gx,) = paddle.grad(f, [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 30.0, rtol=1e-6)
    (gxy,) = paddle.grad(gx, [y])
    np.testing.assert_allclose(gxy.numpy(), 6.0, rtol=1e-6)


def test_gradient_penalty_pattern():
    # WGAN-GP style: loss = (|df/dx| - 1)^2, backward to parameter grads.
    w = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    x = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
    f = w * x * x  # df/dx = 2wx
    (gx,) = paddle.grad(f, [x], create_graph=True)
    penalty = (gx - 1.0) * (gx - 1.0)
    penalty.backward()
    # d/dw (2wx - 1)^2 = 2(2wx-1) * 2x
    expect = 2 * (2 * 2.0 * 1.5 - 1) * 2 * 1.5
    np.testing.assert_allclose(w.grad.numpy(), expect, rtol=1e-6)


def test_double_backward_matmul():
    # y = sum((x @ w)^2); dy/dw = 2 x^T x w ; d/dx of sum(dy/dw) recovers
    # closed form — check numerically against jax ground truth.
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    xv = rng.standard_normal((3, 4)).astype(np.float32)
    wv = rng.standard_normal((4, 2)).astype(np.float32)

    x = paddle.to_tensor(xv, stop_gradient=False)
    w = paddle.to_tensor(wv, stop_gradient=False)
    y = (x.matmul(w) ** 2).sum()
    (gw,) = paddle.grad(y, [w], create_graph=True)
    (gx2,) = paddle.grad(gw.sum(), [x])

    def f(xa, wa):
        return jnp.sum(jnp.matmul(xa, wa) ** 2)

    gw_fn = jax.grad(f, argnums=1)
    oracle = jax.grad(lambda xa: jnp.sum(gw_fn(xa, jnp.asarray(wv))))(jnp.asarray(xv))
    np.testing.assert_allclose(gx2.numpy(), np.asarray(oracle), rtol=1e-4, atol=1e-5)


def test_pylayer_double_backward():
    class Cube(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor()
            return 3 * x * x * dy

    x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    y = Cube.apply(x)
    (gx,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), 12.0, rtol=1e-6)
    (ggx,) = paddle.grad(gx, [x])
    np.testing.assert_allclose(ggx.numpy(), 12.0, rtol=1e-6)  # 6x at x=2


def test_triple_backward():
    # x^4: derivatives 4x^3, 12x^2, 24x
    x = paddle.to_tensor(np.float32(1.5), stop_gradient=False)
    y = x * x * x * x
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1, [x], create_graph=True)
    (g3,) = paddle.grad(g2, [x])
    np.testing.assert_allclose(g1.numpy(), 4 * 1.5**3, rtol=1e-5)
    np.testing.assert_allclose(g2.numpy(), 12 * 1.5**2, rtol=1e-5)
    np.testing.assert_allclose(g3.numpy(), 24 * 1.5, rtol=1e-5)


def test_create_graph_allow_unused():
    x = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    z = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    y = x * x
    gx, gz = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), 2.0, rtol=1e-6)


def test_create_graph_under_amp():
    # gradient-penalty under auto_cast: the replay must match the AMP-cast
    # dtypes the forward was recorded with
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32),
                         stop_gradient=False)
    w = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32),
                         stop_gradient=False)
    with paddle.amp.auto_cast():
        y = x.matmul(w).sum()
    (gx,) = paddle.grad(y, [x], create_graph=True)
    (ggx,) = paddle.grad((gx * gx).sum(), [w], allow_unused=False)
    assert ggx.shape == [8, 8]


def test_create_graph_inside_no_grad():
    # create_graph builds the backward graph regardless of ambient grad mode
    x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    y = x * x * x
    with paddle.no_grad():
        (gx,) = paddle.grad(y, [x], create_graph=True)
    assert not gx.stop_gradient
    (ggx,) = paddle.grad(gx, [x])
    np.testing.assert_allclose(ggx.numpy(), 12.0, rtol=1e-6)


def test_create_graph_multi_output_node():
    # max pooling style multi-output: use topk which returns (values, indices)
    xv = np.array([1.0, 4.0, 2.0, 3.0], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    vals, _ = paddle.topk(x, k=2)
    s = (vals * vals).sum()
    (gx,) = paddle.grad(s, [x], create_graph=True)
    np.testing.assert_allclose(gx.numpy(), np.array([0, 8, 0, 6], np.float32),
                               rtol=1e-6)
    (ggx,) = paddle.grad((gx * gx).sum(), [x])
    # d/dx sum(gx^2) where gx = 2x at selected positions -> 8x selected
    np.testing.assert_allclose(ggx.numpy(), np.array([0, 32, 0, 24], np.float32),
                               rtol=1e-6)
