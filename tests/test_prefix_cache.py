"""Automatic prefix caching (paddle_tpu/serving/prefix_cache/).

Correctness bar: scheduler outputs are TOKEN-IDENTICAL with the cache on
vs off — including under forced eviction and preempt-resume — against the
same per-request eager `generate()` oracle the r6 preemption tests pinned.
Plus: the refcount protocol (shared blocks never freed under a sharer),
copy-on-write on full-prompt hits, LRU leaf eviction under pool pressure,
zero steady-state recompiles with the cache enabled, the inference-Config
bridge, weight-hot-swap flush, and the serve_bench prefix-share artifact.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.models.kv_cache import KVPoolExhausted
from paddle_tpu.serving import (
    ContinuousBatchingScheduler,
    SchedulerConfig,
)
from paddle_tpu.serving.prefix_cache import (
    PrefixCache,
    RadixTree,
    RefCountingBlockAllocator,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """Same guard as test_serving_sched: XLA:CPU AOT replay corrupts decode
    program numerics; serving tests compile fresh."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=2))


def _eager_oracle(model, prompt, max_new):
    out = model.generate(paddle.to_tensor(prompt[None, :].astype(np.int64)),
                         max_new_tokens=max_new, temperature=0.0)
    return np.asarray(out.numpy())[0]


# ----------------------------------------------- ref-counting allocator

def test_refcount_allocator_basics_and_stats():
    a = RefCountingBlockAllocator(num_blocks=6, block_size=4)
    b = a.allocate(9)                       # 3 blocks, ref 1 each
    assert all(a.ref_count(x) == 1 for x in b)
    assert not a.is_shared(b[0])
    a.incref(b[0])
    assert a.ref_count(b[0]) == 2 and a.is_shared(b[0])
    # occupancy/fragmentation stats keep working under sharing: a shared
    # block still counts ONCE toward occupancy
    assert a.num_used_blocks == 3 and a.num_free_blocks == 3
    assert a.utilization() == pytest.approx(0.5)
    assert a.fragmentation(live_tokens=9) == pytest.approx(0.25)
    # free() is one holder's decref: the shared block survives the first
    a.free(b)
    assert a.num_used_blocks == 1 and a.ref_count(b[0]) == 1
    a.decref(b[0])
    assert a.num_free_blocks == 6 and a.num_used_blocks == 0
    with pytest.raises(RuntimeError, match="double free"):
        a.decref(b[0])
    with pytest.raises(RuntimeError, match="not allocated"):
        a.incref(b[0])


def test_refcount_allocator_eviction_callback_reclaims():
    a = RefCountingBlockAllocator(num_blocks=4, block_size=4)
    held = a.allocate(16)                   # pool fully allocated
    cached = list(held[:2])                 # the "tree" adopts two...
    for b in cached:
        a.incref(b)
    a.free(held)                            # ...and the request retires
    assert a.num_used_blocks == 2           # cached survive, others free

    def evict(n):
        # release up to n cached entries (the PrefixCache protocol)
        k = min(n, len(cached))
        for _ in range(k):
            a.decref(cached.pop())
        return k

    a.set_evict_cb(evict)
    running = a.allocate(8)                 # uses the 2 free, no eviction
    assert len(running) == 2 and len(cached) == 2
    got = a.allocate(8)                     # pool dry -> evicts both cached
    assert len(got) == 2 and not cached
    with pytest.raises(KVPoolExhausted):
        a.allocate(4)                       # nothing evictable remains


# --------------------------------------------------------- radix tree

def test_radix_tree_block_granularity_match_insert():
    t = RadixTree(block_size=4)
    toks = list(range(10))                  # 2 full blocks + partial tail
    adopted = t.insert(toks, [7, 8])
    assert adopted == [7, 8] and len(t) == 2
    # full match is block-aligned; partial third block is never cached
    assert t.match(toks) == [7, 8]
    assert t.match(toks[:6]) == [7]         # only the first block matches
    assert t.match([99] + toks[1:]) == []   # divergence inside block 0
    # dedup: re-inserting the same chunks adopts nothing
    assert t.insert(toks, [1, 2]) == []
    # divergent second block forks a sibling, first block still shared
    other = toks[:4] + [77, 77, 77, 77]
    assert t.insert(other, [3, 4]) == [4]
    assert t.match(other) == [7, 4]


def test_radix_tree_lru_leaf_eviction_and_flush():
    t = RadixTree(block_size=2)
    t.insert([1, 2, 3, 4], [10, 11])        # chain: 10 -> 11
    t.insert([5, 6], [12])                  # leaf: 12
    t.match([1, 2, 3, 4])                   # chain is now most recent
    # LRU leaf is 12; inner node 10 must not be evicted before leaf 11
    assert t.evict_lru(1) == [12]
    assert t.evict_lru(2) == [11, 10]       # leaves-first, chain unwinds
    assert len(t) == 0
    t.insert([1, 2], [9])
    assert sorted(t.flush()) == [9] and len(t) == 0


def test_prefix_cache_pin_protocol_and_eviction_preference():
    a = RefCountingBlockAllocator(num_blocks=4, block_size=2)
    pc = PrefixCache(a, block_size=2)
    b1 = a.allocate(4)                      # request 1's two blocks
    pc.insert([1, 2, 3, 4], b1)             # tree adopts (ref 2)
    a.free(b1)                              # request exits (ref 1: tree)
    assert a.num_used_blocks == 2
    pinned = pc.match_and_pin([1, 2, 3, 4])
    assert pinned == b1 and all(a.ref_count(x) == 2 for x in b1)
    got = a.allocate(4)                     # the 2 free blocks, no eviction
    assert len(got) == 2
    # pressure with only PINNED cache entries left: the tree unwinds (the
    # pinner becomes sole owner) but the blocks are NOT freed under it —
    # the pool is genuinely exhausted
    with pytest.raises(KVPoolExhausted):
        a.allocate(2)
    assert pc.stats()["evicted_blocks"] == 2
    assert all(a.ref_count(x) == 1 for x in pinned)   # pin survived
    assert pc.stats()["cached_blocks"] == 0
    pc.unpin(pinned)                        # last holder -> truly free now
    assert a.num_free_blocks == 2


# ------------------------------------------ scheduler: token identity

def _mk(model, enable, **kw):
    cfg = dict(max_num_seqs=2, max_seq_len=64, block_size=8,
               enable_prefix_caching=enable)
    cfg.update(kw)
    return ContinuousBatchingScheduler(model, SchedulerConfig(**cfg))


def test_shared_prefix_workload_token_identical_and_hits(model):
    rng = np.random.default_rng(0)
    shared = rng.integers(0, 1000, 24)
    prompts = [np.concatenate([shared, rng.integers(0, 1000, int(n))])
               for n in rng.integers(4, 10, 6)]
    off = _mk(model, False).generate(prompts, max_new_tokens=5)
    sched = _mk(model, True)
    on = sched.generate(prompts, max_new_tokens=5)
    for p, a, b in zip(prompts, off, on):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(b, _eager_oracle(model, p, 5))
    st = sched.prefix_cache_stats()
    assert st["hit_tokens"] > 0, "shared 24-token prefix must hit"
    assert st["cached_blocks"] > 0
    # hit tokens were NOT prefilled: the miss counter is the prefill work
    assert sched.metrics.prefill_tokens == st["miss_tokens"]
    # registry face: counters + hit-rate gauge exported per scheduler
    prom = sched.metrics.prometheus_text()
    assert "serving_prefix_cache_hit_tokens_total" in prom
    assert "serving_prefix_cache_hit_rate" in prom


def test_full_prompt_hit_copy_on_write_token_identical(model):
    """An exactly-repeated prompt (length = block multiple) is a FULL hit:
    one token is kept to recompute, which partially rewrites the final
    shared block — it must be forked copy-on-write, and every later
    identical request must still decode identically (a corrupted shared
    block would diverge request 3+)."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 1000, 16)      # 2 exact blocks of 8
    ref = _eager_oracle(model, prompt, 6)
    sched = _mk(model, True)
    for _ in range(3):                      # sequential: each later one hits
        out = sched.generate([prompt], max_new_tokens=6)[0]
        np.testing.assert_array_equal(out, ref)
    st = sched.prefix_cache_stats()
    # requests 2 and 3 each matched P-1 = 15 tokens (the CoW cap)
    assert st["hit_tokens"] >= 30


def test_forced_eviction_cycles_token_identical(model):
    """Pool far smaller than the retired-KV footprint: the tree must evict
    LRU blocks continuously, and every output must still match eager."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 1000, int(n))
               for n in rng.integers(9, 20, 8)]
    sched = _mk(model, True, num_blocks=8, max_num_seqs=2)  # 64-token pool
    outs = sched.generate(prompts, max_new_tokens=5)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _eager_oracle(model, p, 5))
    st = sched.prefix_cache_stats()
    assert st["evicted_blocks"] > 0, "pool was sized to force eviction"
    # no leak: flushing the tree returns the whole pool
    sched.prefix_cache.flush()
    assert sched.allocator.num_free_blocks == sched.allocator.num_blocks


def test_preempt_resume_with_cache_forced_eviction_drill(model):
    """The r6 preemption oracle with the cache ON: the pool is sized so
    both sequences admit but cannot both finish — the younger is
    preempted (donating its KV to the tree), cached blocks are evicted
    under continued decode pressure while it waits, and its resume (which
    may partially hit its own donated blocks) stays token-identical."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1000, 10), rng.integers(0, 1000, 9)]
    sched = _mk(model, True, block_size=4, num_blocks=6, max_num_seqs=2)
    outs = sched.generate(prompts, max_new_tokens=8)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _eager_oracle(model, p, 8))
    m = sched.metrics.snapshot()
    st = sched.prefix_cache_stats()
    assert m["preemptions"] >= 1, "pool was sized to force a preemption"
    assert st["evicted_blocks"] >= 1, "resume under pressure must evict"


def test_zero_steady_state_recompiles_with_cache(model):
    """Hit blocks are block-table DATA, not program shapes: after warmup
    covers the suffix buckets, a whole second workload (hits, CoW forks,
    evictions included) must not compile anything new."""
    rng = np.random.default_rng(4)
    shared = rng.integers(0, 1000, 16)

    def workload(seed):
        r = np.random.default_rng(seed)
        return [np.concatenate([shared, r.integers(0, 1000, 8)])
                for _ in range(4)]

    sched = _mk(model, True)
    sched.generate(workload(10), max_new_tokens=4)
    # repeat one prompt exactly -> the CoW path is inside warmup too
    sched.generate(workload(10)[:1], max_new_tokens=4)
    programs = sched.num_programs()
    sched.mark_steady()
    sched.generate(workload(11), max_new_tokens=4)
    sched.generate(workload(11)[:1], max_new_tokens=4)
    stats = sched.compile_stats()
    assert stats["steady_state_recompiles"] == 0
    assert sched.num_programs() == programs


# ------------------------------------------------- integration faces

def test_inference_config_bridges_prefix_caching():
    from paddle_tpu.inference import Config

    cfg = Config()
    cfg.enable_prefix_caching()
    sc = cfg.to_scheduler_config()
    assert sc.enable_prefix_caching is True
    assert Config().to_scheduler_config().enable_prefix_caching is False
    cfg2 = Config()
    cfg2.enable_prefix_caching(False)
    assert cfg2.to_scheduler_config().enable_prefix_caching is False


def test_reload_weights_flushes_prefix_cache(model, tmp_path):
    """Weight hot-swap invalidates every cached block: stale-weight KV
    must never seed a new-weight decode."""
    from paddle_tpu.checkpoint import CheckpointManager

    rng = np.random.default_rng(5)
    sched = _mk(model, True)
    prompt = rng.integers(0, 1000, 12)
    sched.generate([prompt], max_new_tokens=4)
    assert sched.prefix_cache_stats()["cached_blocks"] > 0
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, model=model)
    step = sched.reload_weights(mgr)
    assert step == 1
    assert sched.prefix_cache_stats()["cached_blocks"] == 0
    # same weights were reloaded -> decode still matches eager
    out = sched.generate([prompt], max_new_tokens=4)[0]
    np.testing.assert_array_equal(out, _eager_oracle(model, prompt, 4))


def test_prefix_match_span_recorded(model):
    from paddle_tpu.profiler import Profiler

    rng = np.random.default_rng(6)
    sched = _mk(model, True)
    prof = Profiler(timer_only=False)
    prof.start()
    sched.generate([rng.integers(0, 1000, 10)], max_new_tokens=3)
    prof.stop()
    assert "serving.prefix_match" in prof.summary()


# -------------------------------------------------- satellite: pallas

def test_pallas_package_exports_and_manifest():
    """ops/pallas re-exports entry points + KERNELS manifest, while the
    module attributes (which carry routing state like _FLASH_ENABLED)
    stay importable as modules."""
    import types

    from paddle_tpu.ops import pallas

    assert isinstance(pallas.flash_attention, types.ModuleType)
    assert isinstance(pallas.fused_adamw, types.ModuleType)
    assert isinstance(pallas.fused_rms_norm, types.ModuleType)
    assert callable(pallas.scaled_dot_product_attention)
    assert callable(pallas.fused_adamw_flat)
    assert callable(pallas.rms_norm_routed)
    assert set(pallas.KERNELS) == {"flash_attention", "fused_adamw",
                                   "fused_rms_norm"}
    for k, spec in pallas.KERNELS.items():
        assert callable(spec["entry"]), k
        assert spec["gate"] is None or callable(spec["gate"]), k
        assert spec["module"].startswith("paddle_tpu.ops.pallas."), k


# -------------------------------------------- serve_bench prefix mode

def test_serve_bench_prefix_share_writes_artifact(tmp_path):
    """Offline shared-system-prompt sweep; refreshes the repo-root
    BENCH_serving_prefix.json artifact (TTFT + hit rate at share
    0/0.5/0.9, cache on vs off)."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)

    out = tmp_path / "BENCH_serving_prefix.json"
    artifact = sb.main(["--prefix-share", "--smoke", "--out", str(out)])
    on_disk = json.loads(out.read_text())
    assert on_disk["bench"] == "serving_prefix_cache"
    assert set(on_disk["share"]) == {"0.0", "0.5", "0.9"}
    assert on_disk["share"]["0.9"]["prefix_cache"]["hit_rate"] > 0
    assert on_disk["share"]["0.0"]["prefix_cache"]["hit_rate"] == 0
    assert on_disk["baseline_no_cache"]["0.9"]["prefix_cache"] is None
    assert on_disk["prefill_tokens_saved_at_top_share"] > 0
    assert "ttft_reduction_pct_at_top_share" in on_disk
    # the on-disk form is the canonicalized artifact (sorted keys, stable
    # floats — no-change re-runs must be no-diff)
    from tools.bench_io import canonical, write_bench_json

    assert on_disk == canonical(artifact)
    root_art = os.path.join(REPO, "BENCH_serving_prefix.json")
    write_bench_json(root_art, on_disk)
