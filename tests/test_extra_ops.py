"""Late-round op additions: diff/trapezoid/unfold/renorm/cdist,
grid_sample/affine_grid/fold, huber/poisson-nll/pairwise, CTC loss
(reference patterns: test_ctc_loss_op.py brute-force small cases,
test_grid_sample_op.py identity transforms)."""

import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_diff_trapezoid(rng):
    x = paddle.to_tensor(np.array([1.0, 3.0, 6.0, 10.0], np.float32))
    np.testing.assert_allclose(paddle.diff(x).numpy(), [2.0, 3.0, 4.0])
    np.testing.assert_allclose(
        float(paddle.trapezoid(x).numpy()),
        np.trapezoid([1.0, 3.0, 6.0, 10.0]))


def test_unfold_windows():
    u = paddle.unfold(
        paddle.to_tensor(np.arange(10.0, dtype=np.float32)), 0, 4, 2)
    assert u.shape == [4, 4]
    np.testing.assert_allclose(u.numpy()[0], [0, 1, 2, 3])
    np.testing.assert_allclose(u.numpy()[-1], [6, 7, 8, 9])


def test_renorm_caps_norms(rng):
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32) * 10)
    out = paddle.renorm(x, 2.0, 0, 1.0).numpy()
    norms = np.linalg.norm(out, axis=1)
    assert (norms <= 1.0 + 1e-4).all()


def test_cdist_euclidean(rng):
    a = rng.standard_normal((3, 4)).astype(np.float32)
    b = rng.standard_normal((5, 4)).astype(np.float32)
    d = paddle.cdist(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    ref = np.sqrt(((a[:, None] - b[None]) ** 2).sum(-1))
    np.testing.assert_allclose(d, ref, rtol=1e-4, atol=1e-5)


def test_grid_sample_identity(rng):
    img = paddle.to_tensor(rng.standard_normal((1, 2, 5, 5)).astype(np.float32))
    theta = paddle.to_tensor(
        np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32))
    grid = F.affine_grid(theta, [1, 2, 5, 5])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(out.numpy(), img.numpy(), rtol=1e-4, atol=1e-5)


def test_grid_sample_shift_zeros_padding(rng):
    img = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
    # shift fully out of bounds -> zeros under zeros padding
    theta = paddle.to_tensor(
        np.array([[[1.0, 0, 4.0], [0, 1.0, 4.0]]], np.float32))
    grid = F.affine_grid(theta, [1, 1, 4, 4])
    out = F.grid_sample(img, grid)
    np.testing.assert_allclose(out.numpy(), 0.0)


def test_fold_inverts_sum_of_patches():
    # non-overlapping 2x2 patches: fold reassembles exactly
    col = np.arange(16, dtype=np.float32).reshape(1, 4, 4)  # C=1, kh*kw=4, L=4
    out = F.fold(paddle.to_tensor(col), (4, 4), 2, strides=2).numpy()
    assert out.shape == (1, 1, 4, 4)
    # patch L ordering: row-major over output grid
    np.testing.assert_allclose(out[0, 0, :2, :2],
                               col[0, :, 0].reshape(2, 2))


def test_huber_and_poisson_losses(rng):
    x = paddle.to_tensor(np.array([0.1, 2.0], np.float32))
    y = paddle.to_tensor(np.array([0.0, 0.0], np.float32))
    h = float(F.huber_loss(x, y, delta=1.0, reduction="none").numpy()[1])
    assert abs(h - (2.0 - 0.5)) < 1e-5  # linear branch: delta*(|d|-delta/2)
    p = F.poisson_nll_loss(paddle.to_tensor(np.array([0.5], np.float32)),
                           paddle.to_tensor(np.array([2.0], np.float32)))
    np.testing.assert_allclose(float(p.numpy()),
                               np.exp(0.5) - 2 * 0.5, rtol=1e-5)


def test_ctc_loss_matches_brute_force():
    T, V = 3, 3
    lp = np.log(np.full((T, 1, V), 1 / V, np.float32))
    for target in ([1], [1, 2], [2]):
        S = len(target)
        lab = np.zeros((1, 2), np.int64)
        lab[0, :S] = target
        loss = F.ctc_loss(
            paddle.to_tensor(lp), paddle.to_tensor(lab),
            paddle.to_tensor(np.array([T])),
            paddle.to_tensor(np.array([S])), reduction="none")
        p = 0.0
        for path in itertools.product(range(V), repeat=T):
            col = [k for k, g in itertools.groupby(path) if k != 0]
            if col == target:
                p += (1 / V) ** T
        np.testing.assert_allclose(float(loss.numpy()[0]), -np.log(p),
                                   rtol=1e-4)


def test_ctc_loss_grad_flows(rng):
    lp_np = rng.standard_normal((4, 2, 5)).astype(np.float32)
    lp_np = lp_np - np.log(np.exp(lp_np).sum(-1, keepdims=True))
    lp = paddle.to_tensor(lp_np, stop_gradient=False)
    lab = paddle.to_tensor(np.array([[1, 2], [3, 0]], np.int64))
    loss = F.ctc_loss(lp, lab, paddle.to_tensor(np.array([4, 4])),
                      paddle.to_tensor(np.array([2, 1])))
    loss.backward()
    g = lp.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_concat_dataset():
    from paddle_tpu.io import ConcatDataset, TensorDataset

    a = TensorDataset([paddle.to_tensor(np.arange(3, dtype=np.float32))])
    b = TensorDataset([paddle.to_tensor(np.arange(10.0, 12.0,
                                                  dtype=np.float32))])
    cat = ConcatDataset([a, b])
    assert len(cat) == 5
    assert float(cat[0][0].numpy()) == 0.0
    assert float(cat[3][0].numpy()) == 10.0
