"""Zero-stall training hot path: DevicePrefetcher, TrainStep donation
(+ alias-safety audit + NonBlockingStepResult), overlapped ZeRO-3 fetch,
and the stamped compile cache."""

import json
import os
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io.dataloader import DataLoader, DevicePrefetcher
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.jit.api import NonBlockingStepResult, TrainStep

warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


class _Seq(Dataset):
    def __len__(self):
        return 10

    def __getitem__(self, i):
        return np.float32(i)


def _batches(it):
    return [np.asarray(b.numpy()).ravel().tolist() for b in it]


# ------------------------------------------------------- DevicePrefetcher


def test_prefetcher_yields_identical_sequence():
    plain = _batches(DataLoader(_Seq(), batch_size=2, shuffle=False))
    for depth in (0, 1, 3):
        pf = DevicePrefetcher(DataLoader(_Seq(), batch_size=2,
                                         shuffle=False), depth=depth)
        assert _batches(pf) == plain, f"depth {depth}"
        assert pf.state_dict() == {"epoch": 1, "offset": 0}


def test_prefetcher_counts_consumed_not_buffered():
    """The state cursor moves with the CONSUMER: with depth 3 the producer
    runs ahead, but abandoning after 2 batches must report offset 2."""
    pf = DevicePrefetcher(DataLoader(_Seq(), batch_size=2, shuffle=False),
                          depth=3)
    it = iter(pf)
    next(it), next(it)
    it.close()  # abandon mid-epoch
    assert pf.state_dict() == {"epoch": 0, "offset": 2}
    # a fresh (non-resumed) iteration starts the epoch over
    assert _batches(pf) == _batches(
        DataLoader(_Seq(), batch_size=2, shuffle=False))


def test_prefetcher_resume_mid_epoch_no_off_by_depth():
    """Satellite regression: checkpoint/resume mid-epoch with prefetch
    depth > 0 replays the identical remaining sequence — the buffered
    (fetched-but-unconsumed) batches must not be skipped."""
    pf = DevicePrefetcher(DataLoader(_Seq(), batch_size=2, shuffle=False),
                          depth=2)
    it = iter(pf)
    seen = [next(it) for _ in range(3)]
    del seen
    state = pf.state_dict()
    assert state == {"epoch": 0, "offset": 3}
    it.close()

    pf2 = DevicePrefetcher(DataLoader(_Seq(), batch_size=2, shuffle=False),
                           depth=2)
    pf2.set_state_dict(state)
    rest = _batches(pf2)
    assert rest == [[6.0, 7.0], [8.0, 9.0]]  # continues at batch 3
    assert pf2.state_dict() == {"epoch": 1, "offset": 0}


def test_prefetcher_checkpoint_manager_roundtrip(tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager

    paddle.seed(0)
    m = nn.Linear(2, 2)
    pf = DevicePrefetcher(DataLoader(_Seq(), batch_size=2, shuffle=False),
                          depth=2)
    it = iter(pf)
    for _ in range(3):
        next(it)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, model=m, dataloader=pf)
    it.close()

    pf2 = DevicePrefetcher(DataLoader(_Seq(), batch_size=2, shuffle=False),
                           depth=2)
    mgr.restore(model=m, dataloader=pf2)
    assert _batches(pf2) == [[6.0, 7.0], [8.0, 9.0]]


def test_prefetcher_propagates_worker_error():
    class Bad(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            if i >= 2:
                raise ValueError("boom at 2")
            return np.float32(i)

    pf = DevicePrefetcher(DataLoader(Bad(), batch_size=1, shuffle=False),
                          depth=2)
    with pytest.raises(ValueError, match="boom at 2"):
        list(pf)


def test_prefetcher_meters_input_stall():
    from paddle_tpu.observability.train_stall import input_stall_counter

    before = input_stall_counter().value
    list(DevicePrefetcher(DataLoader(_Seq(), batch_size=5), depth=2))
    assert input_stall_counter().value > before  # pops were metered


# ------------------------------------------------- donation + nonblocking


def _build_train(seed=0, **step_kw):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    optimizer = opt.AdamW(learning_rate=1e-2,
                          parameters=model.parameters())
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, a, b: mse(m(a), b), optimizer,
                     **step_kw)
    return model, step


def _batch_pair(rng):
    return (paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32)),
            paddle.to_tensor(rng.standard_normal((4, 1)).astype(np.float32)))


def test_donated_losses_bit_identical_and_buffers_reported():
    """Acceptance pin: donation changes residency, never math — and the
    step reports its donated state/input buffers via cache-probe evidence
    (deleted shells + the caller-side input guard)."""
    rng = np.random.default_rng(3)
    batches = [_batch_pair(rng) for _ in range(4)]
    vals = [(x.numpy().copy(), y.numpy().copy()) for x, y in batches]

    _, step_ref = _build_train(seed=7, donate=False)
    ref = [float(step_ref(x, y).numpy()) for x, y in batches]

    _, step_don = _build_train(seed=7, donate=True, donate_inputs=True,
                               nonblocking=True)
    got = [step_don(paddle.to_tensor(x), paddle.to_tensor(y)).loss_value()
           for x, y in vals]
    assert got == ref  # bit-identical, not allclose

    rep = step_don.donation_report()
    assert rep["donate_inputs"] and rep["inputs_guarded"]
    assert 0 in rep["donate_argnums"] and 4 in rep["donate_argnums"]
    # state buffers really were consumed in place (jax deletes donated
    # buffers whether or not the backend aliased them)
    assert rep["state_buffers_deleted_frac"] == 1.0


def test_donated_input_reread_raises():
    rng = np.random.default_rng(4)
    _, step = _build_train(donate_inputs=True, nonblocking=True)
    x, y = _batch_pair(rng)
    step(x, y).loss_value()
    for reuse in (lambda: x.numpy(), lambda: x.shape, lambda: x + 1.0,
                  lambda: y.numpy()):
        with pytest.raises(RuntimeError, match="donated"):
            reuse()


def test_donation_alias_audit_copies_duplicates():
    """step(x, x) would donate the same buffer twice — XLA rejects that at
    execute time; the audit must copy the duplicate leaf (metered)."""
    from paddle_tpu.observability.train_stall import donation_copy_counter

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8))
    optimizer = opt.SGD(learning_rate=1e-2, parameters=model.parameters())
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, a, b: mse(m(a), b), optimizer,
                     donate_inputs=True, nonblocking=True)
    rng = np.random.default_rng(5)
    x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    before = donation_copy_counter().value
    loss = step(x, x).loss_value()
    assert np.isfinite(loss)
    assert donation_copy_counter().value == before + 1


def test_gradscaler_skip_on_inf_bit_identical_with_donation(rng):
    """Satellite: scaler counters live in the donated pytree (argnum 7);
    the skip-on-inf round trip must stay bit-identical to the non-donated
    path — scale halves, weights untouched, counters equal."""

    def run(donate):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        optimizer = opt.SGD(learning_rate=1e-2,
                            parameters=model.parameters())
        scaler = paddle.amp.GradScaler(
            init_loss_scaling=2.0 ** 10, decr_every_n_nan_or_inf=1,
            incr_every_n_steps=3)
        mse = nn.MSELoss()
        step = TrainStep(model, lambda m, a, b: mse(m(a), b), optimizer,
                         scaler=scaler, donate=donate)
        r = np.random.default_rng(0)
        x = r.standard_normal((8, 8)).astype(np.float32)
        y = r.standard_normal((8, 1)).astype(np.float32)
        for _ in range(3):
            step(paddle.to_tensor(x), paddle.to_tensor(y))
        step(paddle.to_tensor(np.full((8, 8), 1e38, np.float32)),
             paddle.to_tensor(y))  # inf grads: skip + halve
        extra = step.checkpoint_extra()
        w = model[0].weight.numpy().copy()
        return extra, w, scaler.get_loss_scaling()

    extra_ref, w_ref, scale_ref = run(donate=False)
    extra_don, w_don, scale_don = run(donate=True)
    assert extra_ref == extra_don
    assert scale_ref == scale_don == 2.0 ** 10  # 2**11 halved by the skip
    np.testing.assert_array_equal(w_ref, w_don)


def test_nonblocking_result_defers_and_meters_sync():
    from paddle_tpu.observability.train_stall import sync_stall_counter

    rng = np.random.default_rng(6)
    _, step = _build_train(nonblocking=True)
    res = step(*_batch_pair(rng))
    assert isinstance(res, NonBlockingStepResult)
    assert res.loss.shape == []  # device handle, no sync needed
    before = sync_stall_counter().value
    v = res.loss_value()
    assert np.isfinite(v)
    assert sync_stall_counter().value > before
    assert float(res) == v  # repeat reads are stable


# ------------------------------------------------ ZeRO-3 overlapped fetch


def test_stage3_overlapped_fetch_frontier(monkeypatch):
    """The hook-driven frontier dispatches group k+1 before layer k runs:
    fetches happen in execution order, every group is fetched exactly once,
    and the overlap ratio reports (n-1)/n (group 0 cannot overlap)."""
    from paddle_tpu.distributed import sharding
    from paddle_tpu.observability.train_stall import offload_overlap_gauge

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 4),
                          nn.Linear(4, 4))
    parked_ids = {id(p) for p in model.parameters()}
    fetch_log = []

    monkeypatch.setattr(sharding, "_parked",
                        lambda p: id(p) in parked_ids)

    def fake_fetch(params):
        group = [p for p in params if id(p) in parked_ids]
        if group:
            fetch_log.append([p.name for p in group])
            parked_ids.difference_update(id(p) for p in group)

    monkeypatch.setattr(sharding, "_fetch_group", fake_fetch)
    sharding._wrap_forward_param_fetch(model)

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    _ = model(x)
    # 3 param groups (the ReLU owns none), fetched in execution order
    names = [p.name for p in model.parameters()]
    assert [n for g in fetch_log for n in g] == names
    assert len(fetch_log) == 3
    assert not parked_ids  # nothing left behind
    assert offload_overlap_gauge().value == pytest.approx(2.0 / 3.0)

    # second forward with nothing parked: no new fetches, same output path
    fetch_log.clear()
    _ = model(x)
    assert fetch_log == []


def test_stage3_overlap_kill_switch(monkeypatch):
    """PADDLE_TPU_OFFLOAD_OVERLAP=0 restores the one-shot entry fetch."""
    from paddle_tpu.distributed import sharding

    monkeypatch.setenv("PADDLE_TPU_OFFLOAD_OVERLAP", "0")
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    parked_ids = {id(p) for p in model.parameters()}
    calls = []

    monkeypatch.setattr(sharding, "_parked",
                        lambda p: id(p) in parked_ids)

    def fake_fetch(params):
        group = [p for p in params if id(p) in parked_ids]
        calls.append(len(group))
        parked_ids.difference_update(id(p) for p in group)

    monkeypatch.setattr(sharding, "_fetch_group", fake_fetch)
    sharding._wrap_forward_param_fetch(model)
    _ = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert calls == [4]  # ONE batched fetch of all 4 params at entry


# ---------------------------------------------------- stamped compile cache


def _load_compile_cache_module():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "utils",
        "compile_cache.py")
    spec = importlib.util.spec_from_file_location("_cc_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_compile_cache_stamp_and_invalidate(tmp_path):
    cc = _load_compile_cache_module()
    d = str(tmp_path / "jax_cache")
    out = cc.ensure_compile_cache_dir(d)
    assert out == d
    stamp = os.path.join(d, cc.STAMP_NAME)
    assert json.load(open(stamp)) == cc.cache_key()

    # matching stamp: entries survive
    entry = os.path.join(d, "xla_program_abc")
    open(entry, "w").write("aot")
    cc.ensure_compile_cache_dir(d)
    assert os.path.exists(entry)

    # stale stamp (older framework/jax build): entries are wiped, restamped
    json.dump({"paddle_tpu": "0.0.0", "jax": "0.0.0", "jaxlib": "0.0.0"},
              open(stamp, "w"))
    open(entry, "w").write("aot")
    cc.ensure_compile_cache_dir(d)
    assert not os.path.exists(entry)
    assert json.load(open(stamp)) == cc.cache_key()

    # corrupt stamp counts as stale, not a crash
    open(stamp, "w").write("{not json")
    cc.ensure_compile_cache_dir(d)
    assert json.load(open(stamp)) == cc.cache_key()


def test_bench_probe_attempts_env(monkeypatch):
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.delenv("FLAGS_bench_probe_attempts", raising=False)
    assert bench._probe_attempts() == 1  # fast-fail default
    monkeypatch.setenv("FLAGS_bench_probe_attempts", "5")
    assert bench._probe_attempts() == 5
    monkeypatch.setenv("FLAGS_bench_probe_attempts", "bogus")
    assert bench._probe_attempts() == 1
    monkeypatch.setenv("FLAGS_bench_probe_attempts", "0")
    assert bench._probe_attempts() == 1  # at least one probe


# ------------------------------------------------------- loop integrations


def test_hapi_fit_with_device_prefetch():
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.io.dataset import TensorDataset

    paddle.seed(0)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 2)).astype(np.float32))
    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=1e-2,
                                parameters=net.parameters()),
              loss=nn.MSELoss())
    m.fit(TensorDataset([x, y]), batch_size=4, epochs=1, verbose=0,
          device_prefetch=2)
    w = net.weight.numpy()
    assert np.all(np.isfinite(w))


def test_engine_fit_dispatch_ahead_history():
    """Engine.fit defers the loss sync to the epoch boundary; the history
    must still be the per-step float losses, identical to the eager-sync
    run of the same seeded setup."""
    from paddle_tpu.distributed.auto_parallel.static_engine import Engine

    def make():
        paddle.seed(0)
        net = nn.Linear(4, 2)
        mse = nn.MSELoss()
        e = Engine(net, loss=lambda out, y: mse(out, y),
                   optimizer=opt.SGD(learning_rate=1e-2,
                                     parameters=net.parameters()))
        rng = np.random.default_rng(0)
        data = [(paddle.to_tensor(rng.standard_normal((4, 4))
                                  .astype(np.float32)),
                 paddle.to_tensor(rng.standard_normal((4, 2))
                                  .astype(np.float32)))
                for _ in range(5)]
        return e, data

    e1, d1 = make()
    h1 = e1.fit(d1, epochs=1)
    e2, d2 = make()
    h2 = e2.fit(d2, epochs=1, device_prefetch=2)
    assert len(h1) == len(h2) == 5
    assert all(isinstance(v, float) for v in h2)
    assert h1 == h2  # prefetch + deferred sync change timing, not math
