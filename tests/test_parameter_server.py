"""Minimal Parameter Server tests (VERDICT #10): sparse/dense tables,
accessors (SGD/Adagrad/CTR), shrink/save/load, and an embedding model
trained through pull/push — the reference's CPU sparse workload shape.
"""

import os

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (
    CtrAccessor,
    MemorySparseTable,
    PSClient,
    PSServer,
)


@pytest.fixture
def server():
    srv = PSServer()
    yield srv
    srv._tables.clear()


def test_sparse_pull_lazy_init_and_push(server):
    server.add_sparse_table(0, dim=4, accessor="sgd", lr=0.1)
    c = PSClient()
    rows = c.pull_sparse(0, [7, 42, 7])
    assert rows.shape == (3, 4)
    np.testing.assert_allclose(rows[0], rows[2])  # same id -> same row
    assert c.table_size(0) == 2

    before = c.pull_sparse(0, [7])[0]
    g = np.ones((1, 4), np.float32)
    c.push_sparse(0, [7], g)
    after = c.pull_sparse(0, [7])[0]
    np.testing.assert_allclose(after, before - 0.1, rtol=1e-6)


def test_adagrad_accessor_scales_updates(server):
    server.add_sparse_table(1, dim=2, accessor="adagrad", lr=1.0)
    c = PSClient()
    c.pull_sparse(1, [0])
    g = np.asarray([[1.0, 1.0]], np.float32)
    r0 = c.pull_sparse(1, [0])[0]
    c.push_sparse(1, [0], g)
    r1 = c.pull_sparse(1, [0])[0]
    step1 = r0 - r1
    c.push_sparse(1, [0], g)
    r2 = c.pull_sparse(1, [0])[0]
    step2 = r1 - r2
    assert np.all(step2 < step1)  # g2sum grows -> smaller steps


def test_ctr_accessor_shrink(server):
    t = server.add_sparse_table(2, dim=2, accessor="ctr", show_decay=0.5)
    c = PSClient()
    c.pull_sparse(2, [1, 2])
    # feature 1 gets shows/clicks; feature 2 stays cold
    c.push_sparse(2, [1], np.zeros((1, 2), np.float32),
                  show_clicks=[(10.0, 2.0)])
    dropped = c.shrink(2, threshold=1.0)
    assert dropped == 1  # cold feature 2 pruned
    assert c.table_size(2) == 1
    # decayed stats persist on the survivor
    assert t._rows[1][0] == pytest.approx(5.0)


def test_save_load_roundtrip(tmp_path, server):
    server.add_sparse_table(3, dim=3, accessor="sgd")
    c = PSClient()
    rows = c.pull_sparse(3, [5, 6])
    path = str(tmp_path / "table3.pkl")
    c.save(3, path)

    server._tables.clear()
    server.add_sparse_table(3, dim=3, accessor="sgd", seed=999)
    c.load(3, path)
    rows2 = c.pull_sparse(3, [5, 6])
    np.testing.assert_allclose(rows2, rows)


def test_dense_table(server):
    server.add_dense_table(4, dim=8, lr=0.5)
    c = PSClient()
    w0 = c.pull_dense(4)
    c.push_dense(4, np.ones(8, np.float32))
    w1 = c.pull_dense(4)
    np.testing.assert_allclose(w1, w0 - 0.5, rtol=1e-6)


def test_sparse_embedding_model_trains(server):
    """CTR-ish training loop: tiny logistic regression over PS-served
    embeddings — loss must drop (end-to-end pull/push correctness)."""
    dim = 8
    server.add_sparse_table(5, dim=dim, accessor="adagrad", lr=0.5)
    c = PSClient()
    rng = np.random.default_rng(0)
    n_feat = 50
    # ground truth: feature id parity decides the label
    samples = [(rng.integers(0, n_feat, 5), None) for _ in range(64)]
    samples = [(ids, float(np.sum(ids % 2) > 2.5)) for ids, _ in samples]

    losses = []
    for epoch in range(30):
        total = 0.0
        for ids, y in samples:
            emb = c.pull_sparse(5, ids)            # [5, dim]
            z = float(emb.sum())
            p = 1.0 / (1.0 + np.exp(-z))
            total += -(y * np.log(p + 1e-9)
                       + (1 - y) * np.log(1 - p + 1e-9))
            gz = p - y
            grads = np.full((len(ids), dim), gz / dim, np.float32)
            c.push_sparse(5, ids, grads)
        losses.append(total / len(samples))
    assert losses[-1] < 0.5 * losses[0]
