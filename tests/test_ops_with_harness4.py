"""Round-2 op-test depth (VERDICT r1 weak #5): a table-driven OpTest sweep.

Each CASES entry runs through the OpTest harness (eager + jit vs numpy,
central-difference gradients). TOLERANCES is the tolerance-governance
analogue of the reference's test/white_list/op_accuracy_white_list.py:
every op gets the strict default unless it is explicitly listed with a
justification.
"""

import numpy as np
import pytest
import scipy.special

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from tests.op_test import OpTest

rng = np.random.default_rng(7)


def _f32(*shape, positive=False, lo=-2.0, hi=2.0):
    a = rng.uniform(lo, hi, shape).astype(np.float32)
    if positive:
        a = np.abs(a) + 0.5
    return a


# op-accuracy governance: name -> (rtol, atol, why)
TOLERANCES = {
    "lgamma": (1e-4, 1e-5, "polynomial approximation differs from scipy"),
    "digamma": (1e-4, 1e-5, "polynomial approximation differs from scipy"),
    "erfinv": (1e-4, 1e-5, "iterative inverse"),
    "logsumexp": (1e-5, 1e-6, "reduction order"),
    "matrix_power": (1e-4, 1e-5, "repeated matmul accumulates"),
    "pinv": (1e-4, 1e-4, "svd-based"),
    "dist": (1e-5, 1e-6, "norm reduction order"),
}
_DEFAULT_TOL = (1e-6, 1e-7)

# (name, op, inputs, attrs, ref, grad_keys)
CASES = [
    # ---------------------------------------------------------- unary math
    ("erf", paddle.erf, {"x": _f32(3, 4)}, {}, scipy.special.erf, ["x"]),
    ("erfinv", paddle.erfinv, {"x": _f32(3, 4, lo=-0.9, hi=0.9)}, {},
     scipy.special.erfinv, ["x"]),
    ("lgamma", paddle.lgamma, {"x": _f32(3, 4, positive=True)}, {},
     scipy.special.gammaln, ["x"]),
    ("digamma", paddle.digamma, {"x": _f32(3, 4, positive=True)}, {},
     scipy.special.digamma, ["x"]),
    ("expm1", paddle.expm1, {"x": _f32(3, 4)}, {}, np.expm1, ["x"]),
    ("log1p", paddle.log1p, {"x": _f32(3, 4, positive=True)}, {},
     np.log1p, ["x"]),
    ("rsqrt", paddle.rsqrt, {"x": _f32(3, 4, positive=True)}, {},
     lambda x: 1.0 / np.sqrt(x), ["x"]),
    ("sinh", paddle.sinh, {"x": _f32(3, 4)}, {}, np.sinh, ["x"]),
    ("cosh", paddle.cosh, {"x": _f32(3, 4)}, {}, np.cosh, ["x"]),
    ("asinh", paddle.asinh, {"x": _f32(3, 4)}, {}, np.arcsinh, ["x"]),
    ("acosh", paddle.acosh, {"x": _f32(3, 4, positive=True, lo=1.5, hi=3)},
     {}, np.arccosh, ["x"]),
    ("atanh", paddle.atanh, {"x": _f32(3, 4, lo=-0.8, hi=0.8)}, {},
     np.arctanh, ["x"]),
    ("floor", paddle.floor, {"x": _f32(3, 4)}, {}, np.floor, None),
    ("ceil", paddle.ceil, {"x": _f32(3, 4)}, {}, np.ceil, None),
    ("round", paddle.round, {"x": _f32(3, 4)}, {}, np.round, None),
    ("trunc", paddle.trunc, {"x": _f32(3, 4)}, {}, np.trunc, None),
    ("frac", paddle.frac, {"x": _f32(3, 4)}, {},
     lambda x: x - np.trunc(x), ["x"]),
    ("sign", paddle.sign, {"x": _f32(3, 4)}, {}, np.sign, None),
    ("reciprocal", paddle.reciprocal, {"x": _f32(3, 4, positive=True)}, {},
     lambda x: 1.0 / x, ["x"]),
    ("square", paddle.square, {"x": _f32(3, 4)}, {}, np.square, ["x"]),
    ("angle", paddle.angle, {"x": _f32(3, 4)}, {}, np.angle, None),
    # --------------------------------------------------------- binary math
    ("atan2", paddle.atan2, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.arctan2, ["x", "y"]),
    ("heaviside", paddle.heaviside, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.heaviside, None),
    ("fmax", paddle.fmax, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.fmax, None),
    ("fmin", paddle.fmin, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.fmin, None),
    ("hypot", paddle.hypot, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.hypot, ["x", "y"]),
    ("copysign", paddle.copysign, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.copysign, None),
    ("logaddexp", paddle.logaddexp, {"x": _f32(3, 4), "y": _f32(3, 4)}, {},
     np.logaddexp, ["x", "y"]),
    ("remainder", paddle.remainder,
     {"x": _f32(3, 4), "y": _f32(3, 4, positive=True)}, {},
     np.remainder, None),
    # ---------------------------------------------------------- reductions
    ("logsumexp", paddle.logsumexp, {"x": _f32(3, 5)}, {"axis": 1},
     lambda x, axis: scipy.special.logsumexp(x, axis=axis), ["x"]),
    ("prod", paddle.prod, {"x": _f32(3, 4, positive=True)}, {"axis": 1},
     lambda x, axis: np.prod(x, axis=axis), ["x"]),
    ("amax", paddle.amax, {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.max(x, axis=axis), None),
    ("amin", paddle.amin, {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.min(x, axis=axis), None),
    ("nansum", paddle.nansum, {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.nansum(x, axis=axis), ["x"]),
    ("nanmean", paddle.nanmean, {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.nanmean(x, axis=axis), ["x"]),
    ("median", paddle.median, {"x": _f32(1, 7)}, {"axis": 1},
     lambda x, axis: np.median(x, axis=axis), None),
    ("std", paddle.std, {"x": _f32(3, 6)}, {"axis": 1},
     lambda x, axis: np.std(x, axis=axis, ddof=1), ["x"]),
    ("var", paddle.var, {"x": _f32(3, 6)}, {"axis": 1},
     lambda x, axis: np.var(x, axis=axis, ddof=1), ["x"]),
    ("count_nonzero", paddle.count_nonzero,
     {"x": (np.asarray([[0, 1, 2], [3, 0, 0]], np.float32))}, {"axis": 1},
     lambda x, axis: np.count_nonzero(x, axis=axis), None),
    # -------------------------------------------------------- manipulation
    ("tile", paddle.tile, {"x": _f32(2, 3)}, {"repeat_times": [2, 2]},
     lambda x, repeat_times: np.tile(x, repeat_times), ["x"]),
    ("roll", paddle.roll, {"x": _f32(3, 4)}, {"shifts": 1, "axis": 1},
     lambda x, shifts, axis: np.roll(x, shifts, axis), ["x"]),
    ("flip", paddle.flip, {"x": _f32(3, 4)}, {"axis": [1]},
     lambda x, axis: np.flip(x, axis), ["x"]),
    ("rot90", paddle.rot90, {"x": _f32(3, 4)}, {},
     lambda x: np.rot90(x), ["x"]),
    ("broadcast_to", paddle.broadcast_to, {"x": _f32(1, 4)},
     {"shape": [3, 4]},
     lambda x, shape: np.broadcast_to(x, shape), ["x"]),
    ("flatten", paddle.flatten, {"x": _f32(2, 3, 4)}, {},
     lambda x: x.reshape(-1), None),
    ("tril", paddle.tril, {"x": _f32(4, 4)}, {}, np.tril, ["x"]),
    ("triu", paddle.triu, {"x": _f32(4, 4)}, {}, np.triu, ["x"]),
    ("diagonal", paddle.diagonal, {"x": _f32(4, 4)}, {},
     lambda x: np.diagonal(x), None),
    ("trace", paddle.trace, {"x": _f32(4, 4)}, {},
     lambda x: np.trace(x), ["x"]),
    ("diagflat", paddle.diagflat, {"x": _f32(4)}, {}, np.diagflat, None),
    ("take_along_axis", paddle.take_along_axis,
     {"arr": _f32(3, 4),
      "indices": rng.integers(0, 4, (3, 2)).astype(np.int64)}, {"axis": 1},
     lambda arr, indices, axis: np.take_along_axis(arr, indices, axis),
     None),
    ("index_select", paddle.index_select,
     {"x": _f32(4, 3), "index": np.asarray([0, 2], np.int64)}, {"axis": 0},
     lambda x, index, axis: np.take(x, index, axis), None),
    ("repeat_interleave", paddle.repeat_interleave, {"x": _f32(2, 3)},
     {"repeats": 2, "axis": 1},
     lambda x, repeats, axis: np.repeat(x, repeats, axis), None),
    ("cumsum", paddle.cumsum, {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.cumsum(x, axis), ["x"]),
    ("cumprod", paddle.cumprod, {"x": _f32(3, 4, positive=True)},
     {"dim": 1},
     lambda x, dim: np.cumprod(x, dim), ["x"]),
    ("cummax", lambda x, axis: paddle.cummax(x, axis=axis)[0],
     {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.maximum.accumulate(x, axis), None),
    ("cummin", lambda x, axis: paddle.cummin(x, axis=axis)[0],
     {"x": _f32(3, 4)}, {"axis": 1},
     lambda x, axis: np.minimum.accumulate(x, axis), None),
    # ------------------------------------------------------------- linalg
    ("matrix_power", paddle.linalg.matrix_power, {"x": _f32(3, 3)},
     {"n": 3}, lambda x, n: np.linalg.matrix_power(x, n), None),
    ("det", paddle.linalg.det, {"x": _f32(3, 3) + 2 * np.eye(3, dtype=np.float32)},
     {}, np.linalg.det, None),
    ("pinv", paddle.linalg.pinv, {"x": _f32(4, 3)}, {},
     np.linalg.pinv, None),
    ("dist", paddle.dist, {"x": _f32(3, 4), "y": _f32(3, 4)}, {"p": 2},
     lambda x, y, p: np.linalg.norm((x - y).ravel(), ord=p), ["x", "y"]),
    # ------------------------------------------------------------- losses
    ("mse_loss", F.mse_loss, {"input": _f32(4, 3), "label": _f32(4, 3)},
     {}, lambda input, label: np.mean((input - label) ** 2), ["input"]),
    ("l1_loss", F.l1_loss, {"input": _f32(4, 3), "label": _f32(4, 3)},
     {}, lambda input, label: np.mean(np.abs(input - label)), None),
    ("log_loss", __import__(
        "paddle_tpu.ops.extra_math", fromlist=["log_loss"]).log_loss,
     {"input": _f32(4, 1, lo=0.1, hi=0.9), "label": _f32(4, 1, lo=0, hi=1)},
     {},
     lambda input, label: -label * np.log(input + 1e-4)
     - (1 - label) * np.log(1 - input + 1e-4), ["input"]),
    # --------------------------------------------------------- activation
    ("glu", F.glu, {"x": _f32(3, 8)}, {},
     lambda x: x[:, :4] * (1 / (1 + np.exp(-x[:, 4:]))), ["x"]),
    ("softplus", F.softplus, {"x": _f32(3, 4)}, {},
     lambda x: np.log1p(np.exp(x)), ["x"]),
    ("hardswish", F.hardswish, {"x": _f32(3, 4, lo=-4, hi=4)}, {},
     lambda x: x * np.clip(x + 3, 0, 6) / 6, ["x"]),
    ("elu", F.elu, {"x": _f32(3, 4)}, {"alpha": 1.0},
     lambda x, alpha: np.where(x > 0, x, alpha * np.expm1(x)), ["x"]),
    ("celu", F.celu, {"x": _f32(3, 4)}, {"alpha": 1.2},
     lambda x, alpha: np.maximum(x, 0)
     + np.minimum(0, alpha * np.expm1(x / alpha)), ["x"]),
    ("selu", F.selu, {"x": _f32(3, 4)}, {},
     lambda x: np.where(x > 0, 1.0507009873554805 * x,
                        1.0507009873554805 * 1.6732632423543772
                        * np.expm1(x)), ["x"]),
    ("mish", F.mish, {"x": _f32(3, 4)}, {},
     lambda x: x * np.tanh(np.log1p(np.exp(x))), ["x"]),
    ("logsigmoid", F.log_sigmoid, {"x": _f32(3, 4)}, {},
     lambda x: -np.log1p(np.exp(-x)), ["x"]),
]

CASES = [c for c in CASES if c[1] is not None]


def _ref_takes_attrs(fn, attrs):
    if not attrs:
        return False
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False  # ufuncs etc.: positional inputs only
    return any(k in sig.parameters for k in attrs)


def _make_ref(ref_fn, input_keys, attrs):
    takes_attrs = _ref_takes_attrs(ref_fn, attrs)

    def ref(**kw):
        pos = [kw[k] for k in input_keys]
        if takes_attrs:
            return ref_fn(*pos, **attrs)
        return ref_fn(*pos)

    return ref


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op_against_numpy(case):
    name, op, inputs, attrs, ref_fn, grad_keys = case
    rtol, atol = TOLERANCES.get(name, _DEFAULT_TOL)[:2]

    class T(OpTest):
        pass

    T.op = staticmethod(op)
    T.attrs = attrs
    t = T()
    t.inputs = inputs
    t.ref = staticmethod(_make_ref(ref_fn, list(inputs), attrs))
    t.check_output(rtol=rtol, atol=atol)
    if grad_keys:
        t.check_grad(grad_keys)


def test_round2_api_surface_sweep():
    """The r2 API probe additions: quick numpy pins for each."""
    import paddle_tpu as paddle
    from paddle_tpu.ops import manipulation as M

    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = paddle.to_tensor

    np.testing.assert_allclose(paddle.sinc(t(x / 7)).numpy(),
                               np.sinc(x / 7), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        paddle.xlogy(t(x), t(x + 1)).numpy(),
        scipy.special.xlogy(x, x + 1), rtol=1e-5, atol=1e-6)
    assert bool(paddle.isposinf(t(np.asarray([np.inf]))).numpy()[0])
    assert bool(paddle.isneginf(t(np.asarray([-np.inf]))).numpy()[0])
    m, e = paddle.frexp(t(np.asarray([8.0], np.float32)))
    assert float(m.numpy()[0]) == 0.5 and int(e.numpy()[0]) == 4

    d = paddle.pdist(t(np.asarray([[0.0, 0], [3, 4], [0, 1]], np.float32)))
    np.testing.assert_allclose(d.numpy(), [5.0, 1.0, np.sqrt(18)], rtol=1e-5)

    np.testing.assert_allclose(
        paddle.vander(t(np.asarray([1.0, 2, 3], np.float32)), 3).numpy(),
        np.vander([1.0, 2, 3], 3), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.nanquantile(t(x), 0.5).numpy(), np.nanquantile(x, 0.5),
        rtol=1e-6)

    np.testing.assert_allclose(
        M.take(t(x), t(np.asarray([0, -1]))).numpy(), [0.0, 11.0])
    out = M.masked_scatter(
        t(np.zeros((2, 2), np.float32)),
        t(np.asarray([[True, False], [False, True]])),
        t(np.asarray([7.0, 8.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [[7, 0], [0, 8]])
    out = M.index_fill(t(x.copy()), t(np.asarray([1])), 0, -1.0)
    assert np.all(out.numpy()[1] == -1.0)
    assert M.unflatten(t(x), 1, [2, 2]).shape == [3, 2, 2]
    out = M.select_scatter(t(x.copy()), t(np.full(4, 9.0, np.float32)), 0, 1)
    assert np.all(out.numpy()[1] == 9.0)
    out = M.slice_scatter(t(x.copy()), t(np.full((3, 2), 5.0, np.float32)),
                          [1], [0], [2])
    assert np.all(out.numpy()[:, :2] == 5.0)
    cs = M.column_stack([t(np.ones(3, np.float32)),
                         t(np.zeros(3, np.float32))])
    assert cs.shape == [3, 2]
    rs = M.row_stack([t(np.ones((1, 3), np.float32)),
                      t(np.zeros((1, 3), np.float32))])
    assert rs.shape == [2, 3]
    hs = M.hsplit(t(x), 2)
    assert len(hs) == 2 and hs[0].shape == [3, 2]
    vs = M.vsplit(t(x), 3)
    assert len(vs) == 3
    ds = M.dsplit(t(x.reshape(3, 2, 2)), 2)
    assert len(ds) == 2


def test_take_modes_and_split_grads():
    import paddle_tpu as paddle
    from paddle_tpu.ops import manipulation as M

    t = paddle.to_tensor
    a = np.arange(6, dtype=np.float32)
    np.testing.assert_allclose(
        M.take(t(a), t(np.asarray([7, 8])), mode="wrap").numpy(), [1.0, 2.0])
    np.testing.assert_allclose(
        M.take(t(a), t(np.asarray([-1, 100])), mode="clip").numpy(),
        [0.0, 5.0])
    with pytest.raises(IndexError):
        M.take(t(a), t(np.asarray([100])))
    # hsplit gradient flows
    x = t(np.arange(12, dtype=np.float32).reshape(3, 4), stop_gradient=False)
    parts = M.hsplit(x, 2)
    (parts[0].sum() + 2 * parts[1].sum()).backward()
    np.testing.assert_allclose(x.grad.numpy()[:, :2], 1.0)
    np.testing.assert_allclose(x.grad.numpy()[:, 2:], 2.0)
