"""SPMD sharding-propagation oracles: GSPMD must propagate shardings the way
the reference's explicit per-op rules do (paddle/phi/infermeta/spmd_rules/
{matmul,embedding,layer_norm,reduction,elementwise}.cc) — SURVEY §2.1 says
those rules serve as test oracles for the GSPMD-delegation design."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

requires_8 = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices")


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "mp"))


def _spec_of(arr):
    return arr.sharding.spec


@requires_8
def test_matmul_row_parallel_propagates_batch_shard():
    # matmul.cc rule: x[M(dp), K] @ w[K, N] -> out[M(dp), N]
    mesh = _mesh()
    x = jax.device_put(np.ones((8, 16), np.float32),
                       NamedSharding(mesh, P("dp", None)))
    w = jax.device_put(np.ones((16, 4), np.float32),
                       NamedSharding(mesh, P(None, None)))
    out = jax.jit(jnp.matmul)(x, w)
    assert _spec_of(out) == P("dp", None), _spec_of(out)


@requires_8
def test_matmul_column_parallel_propagates_out_shard():
    # matmul.cc rule: x[M, K] @ w[K, N(mp)] -> out[M, N(mp)]
    mesh = _mesh()
    x = jax.device_put(np.ones((8, 16), np.float32),
                       NamedSharding(mesh, P(None, None)))
    w = jax.device_put(np.ones((16, 8), np.float32),
                       NamedSharding(mesh, P(None, "mp")))
    out = jax.jit(jnp.matmul)(x, w)
    assert _spec_of(out) == P(None, "mp"), _spec_of(out)


@requires_8
def test_matmul_contracting_shard_allreduces():
    # matmul.cc rule: x[M, K(mp)] @ w[K(mp), N] -> out partial over mp,
    # resolved by an all-reduce; the materialized output must be correct
    # and mp-unsharded (row-parallel linear semantics)
    mesh = _mesh()
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((4, 8)).astype(np.float32)
    wv = rng.standard_normal((8, 4)).astype(np.float32)
    x = jax.device_put(xv, NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(wv, NamedSharding(mesh, P("mp", None)))
    out = jax.jit(jnp.matmul)(x, w)
    np.testing.assert_allclose(np.asarray(out), xv @ wv, rtol=1e-5)
    spec = _spec_of(out)
    assert "mp" not in jax.tree_util.tree_leaves(spec), spec


@requires_8
def test_elementwise_preserves_sharding():
    # elementwise.cc rule: unary ops pass the input dist_attr through
    mesh = _mesh()
    x = jax.device_put(np.ones((8, 8), np.float32),
                       NamedSharding(mesh, P("dp", "mp")))
    out = jax.jit(jnp.tanh)(x)
    assert _spec_of(out) == P("dp", "mp"), _spec_of(out)


@requires_8
def test_reduction_removes_reduced_axis_shard():
    # reduction.cc rule: sum over a sharded axis -> partial -> all-reduced;
    # sum over an unsharded axis keeps the batch shard
    mesh = _mesh()
    x = jax.device_put(np.ones((8, 16), np.float32),
                       NamedSharding(mesh, P("dp", None)))
    out = jax.jit(lambda a: jnp.sum(a, axis=1))(x)
    assert _spec_of(out) == P("dp"), _spec_of(out)


@requires_8
def test_layer_norm_keeps_batch_shard():
    # layer_norm.cc rule: normalized (last) dims replicated, batch dims
    # keep their shard
    mesh = _mesh()
    x = jax.device_put(np.random.rand(8, 16).astype(np.float32),
                       NamedSharding(mesh, P("dp", None)))

    def ln(a):
        mu = a.mean(-1, keepdims=True)
        var = ((a - mu) ** 2).mean(-1, keepdims=True)
        return (a - mu) * jax.lax.rsqrt(var + 1e-5)

    out = jax.jit(ln)(x)
    assert _spec_of(out) == P("dp", None), _spec_of(out)


@requires_8
def test_embedding_vocab_sharded_gather_correct():
    # embedding.cc rule: vocab-sharded table gather -> partial(sum) output
    # resolved to replicated; values must match the unsharded gather
    mesh = _mesh()
    rng = np.random.default_rng(1)
    table = rng.standard_normal((64, 8)).astype(np.float32)
    ids = rng.integers(0, 64, (4, 6))
    t = jax.device_put(table, NamedSharding(mesh, P("mp", None)))
    ids_d = jax.device_put(ids, NamedSharding(mesh, P(None, None)))
    out = jax.jit(lambda tb, i: tb[i])(t, ids_d)
    np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)
