"""r4 model-zoo closure: MobileNetV3 small/large, InceptionV3, ResNeXt
(reference: python/paddle/vision/models/{mobilenetv3,inceptionv3,
resnet}.py). Parameter counts are pinned to the canonical architecture
sizes — a wrong block config cannot hide behind a passing forward."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _n_params(m):
    return sum(int(np.prod(p.shape)) for p in m.parameters())


@pytest.mark.parametrize("ctor,size,params_m", [
    (M.mobilenet_v3_small, 224, 2.54),
    (M.mobilenet_v3_large, 224, 5.48),
    (M.inception_v3, 299, 23.83),
    (M.resnext50_32x4d, 224, 25.03),
])
def test_forward_and_param_count(ctor, size, params_m):
    m = ctor(num_classes=1000)
    n = _n_params(m) / 1e6
    assert abs(n - params_m) < 0.05, (ctor.__name__, n)
    m.eval()
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(2, 3, size, size)).astype(np.float32))
    out = m(x)
    assert tuple(out.shape) == (2, 1000)
    assert np.isfinite(np.asarray(out.numpy())).all()


def test_resnext_variants_construct():
    """Every factory actually BUILDS (a bad kwarg/depth would raise here);
    param counts grow monotonically with depth and cardinality."""
    counts = {}
    for name in ("resnext50_32x4d", "resnext50_64x4d", "resnext101_32x4d",
                 "resnext101_64x4d", "resnext152_32x4d",
                 "resnext152_64x4d"):
        counts[name] = _n_params(getattr(M, name)(num_classes=10))
    assert counts["resnext50_32x4d"] < counts["resnext50_64x4d"]
    assert counts["resnext101_32x4d"] < counts["resnext101_64x4d"]
    assert counts["resnext50_32x4d"] < counts["resnext101_32x4d"] \
        < counts["resnext152_32x4d"]
    # canonical: ResNeXt-101 32x4d is ~42.5M at 10 classes (44.18M @1000)
    assert abs(counts["resnext101_32x4d"] / 1e6 - 42.6) < 1.0, counts


def test_mobilenet_v3_trains():
    import paddle_tpu.optimizer as opt
    from paddle_tpu import nn

    paddle.seed(0)
    m = M.mobilenet_v3_small(num_classes=4, scale=0.5)
    o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                     parameters=m.parameters())
    ce = nn.CrossEntropyLoss()
    rng = np.random.default_rng(1)
    x = paddle.to_tensor(rng.normal(size=(8, 3, 64, 64)).astype(np.float32))
    y = paddle.to_tensor((np.arange(8) % 4).astype(np.int64))
    first = last = None
    m.train()
    for _ in range(6):
        loss = ce(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        v = float(np.asarray(loss.numpy()))
        first = first if first is not None else v
        last = v
    assert last < first, (first, last)
