"""Fleet observability unit faces: journey tracking (FleetTracer), tiered
metrics time-series history (MetricsTimeline), correlated postmortem
bundles (PostmortemStore), and the /debug/timeline + /debug/postmortem
endpoint routes.

The integration face — a real kill drill producing one cross-replica
journey with bit-identical tokens — lives in tests/test_router.py; these
tests pin the primitives' contracts deterministically (explicit
timestamps, no model, no threads unless the test is about the sampler).
"""

import json
import time
import urllib.request

import pytest

from paddle_tpu.observability import (
    FleetTracer,
    MetricsTimeline,
    ObservabilityEndpoint,
    PostmortemStore,
    RequestTracer,
)
from paddle_tpu.observability.fleet import JOURNEY_SPANS, TIMELINE_TIERS
from paddle_tpu.observability.request_trace import PHASE_ADMIT, PHASE_RUNNING


# --------------------------------------------------------------- journeys

def _journey(ft, rid=7, decision="least_loaded"):
    return ft.start(rid, t=100.0, replica_id=0, generation=0,
                    replica_rid=11, decision=decision)


def test_journey_lifecycle_segments_and_spans():
    ft = FleetTracer()
    j = _journey(ft)
    assert j.failovers == 0 and j.arrival_t == 100.0
    assert j.current_segment()["replica_id"] == 0
    # route span is anchored to arrival; no spill for a direct placement
    assert [n for n, *_ in j.spans] == ["route"]
    assert j.spans[0][1] == 100.0
    ft.record_span(7, "reap", 101.0, 101.2, replica=0)
    ft.move(7, replica_id=2, generation=1, replica_rid=31, t=101.5)
    ft.record_span(7, "replay", 101.5, 101.8, committed_tokens=3)
    ft.finish(7, t=103.0, finish_reason="stop")
    assert ft.get(7).failovers == 1
    d = ft.get(7).to_dict()
    assert d["finish_t"] == 103.0 and d["finish_reason"] == "stop"
    assert [s["replica_id"] for s in d["segments"]] == [0, 2]
    names = [s["name"] for s in d["spans"]]
    assert names == ["route", "reap", "replay"]
    assert all(n in JOURNEY_SPANS for n in names)
    reap = d["spans"][1]
    assert reap["dur_s"] == pytest.approx(0.2) and reap["replica"] == 0
    # finished journeys leave the live table but stay queryable
    assert ft.journeys() == [ft.get(7)]
    # spans/moves for unknown (already finished) rids are dropped, not kept
    ft.record_span(7, "reap", 0, 1)
    ft.move(7, replica_id=1, generation=0, replica_rid=1)
    assert ft.get(7).failovers == 1


def test_journey_spill_marker_and_disabled_noop():
    ft = FleetTracer()
    _journey(ft, rid=1, decision="affinity_spill")
    names = [n for n, *_ in ft.get(1).spans]
    assert names == ["route", "spill"]
    spill = ft.get(1).spans[1]
    assert spill[1] == spill[2]          # zero-width marker
    off = FleetTracer(enabled=False)
    assert _journey(off) is None
    off.record_span(7, "reap", 0, 1)
    off.move(7, replica_id=0, generation=0, replica_rid=0)
    off.finish(7)
    assert off.journeys() == [] and off.to_json() == []


def test_journey_ring_bound_and_to_json_last():
    ft = FleetTracer(max_completed=2)
    for rid in range(4):
        _journey(ft, rid=rid)
        ft.finish(rid, t=101.0)
    assert [j.router_rid for j in ft.journeys()] == [2, 3]
    assert ft.get(0) is None
    assert [r["router_rid"] for r in ft.to_json(last=1)] == [3]


def test_fleet_chrome_trace_resolves_replica_timeline():
    """One fleet track interleaves the owning replica's request phases
    (resolved newest-segment-first) with the router-side journey spans;
    a live request gets an open final span."""
    ft = FleetTracer()
    _journey(ft, rid=5)
    ft.move(5, replica_id=1, generation=0, replica_rid=21, t=102.0)
    # the survivor's tracer holds the (resumed) full phase history
    tracer = RequestTracer()
    tr = tracer.start(21, t=100.0)
    tr.transition(PHASE_ADMIT, t=100.5)
    tr.transition(PHASE_RUNNING, t=101.0)

    seen = []

    def resolve(seg):
        seen.append(seg["replica_id"])
        return tracer.get(seg["replica_rid"]) if seg["replica_id"] == 1 \
            else None

    ct = ft.chrome_trace(resolve)
    assert seen == [1]                   # newest-first, first hit wins
    ev = [e for e in ct["traceEvents"] if e.get("tid") == 5]
    names = [e["name"] for e in ev if e.get("ph") == "X"]
    assert "req.queued" in names and "req.admit" in names
    assert "router.route" in names
    meta = [e for e in ev if e.get("ph") == "M"]
    assert len(meta) == 1
    assert meta[0]["args"]["name"] == "request 5 (replica 0→1)"
    live = [e for e in ev if e.get("args", {}).get("open")]
    assert len(live) == 1 and live[0]["name"] == "req.running"
    # no resolver: journey spans only, still one labeled track
    ct2 = ft.chrome_trace()
    names2 = {e["name"] for e in ct2["traceEvents"]
              if e.get("tid") == 5 and e.get("ph") == "X"}
    assert names2 == {"router.route"}


# ---------------------------------------------------------------- timeline

def test_timeline_tiered_retention_and_query():
    tl = MetricsTimeline(tiers=(("raw", 1.0, 3), ("10s", 10.0, 8)))
    state = {"x": 0}
    tl.add_source("src", lambda: {"x": state["x"], "nested": {"y": 2},
                                  "flag": True, "label": "ignored"})
    for i in range(12):
        state["x"] = i
        tl.sample_once(t=1000.0 + i)
    assert tl.samples_taken == 12
    # raw ring is bounded: only the newest 3 of 12 one-second ticks
    raw = tl.query("src.x")
    assert raw == [(1009.0, 9.0), (1010.0, 10.0), (1011.0, 11.0)]
    assert tl.query("src.x", last=1) == [(1011.0, 11.0)]
    # the 10s tier downsampled: first tick then the first one >= 10s later
    assert tl.query("src.x", tier="10s") == [(1000.0, 0.0), (1010.0, 10.0)]
    # numeric leaves flatten to dotted names; bools coerce; strings drop
    assert tl.query("src.nested.y", last=1) == [(1011.0, 2.0)]
    assert tl.query("src.flag", last=1) == [(1011.0, 1.0)]
    assert set(tl.metric_names()) == {"src.x", "src.nested.y", "src.flag"}
    with pytest.raises(KeyError):
        tl.query("src.x", tier="60s")    # not a tier of THIS timeline
    snap = tl.snapshot()
    assert snap["tiers"]["raw"]["retained"] == 3
    assert snap["tiers"]["10s"]["capacity"] == 8
    assert not snap["sampler_alive"]
    # default tiers are the documented 1s/10s/60s ladder
    assert [n for n, _, _ in MetricsTimeline().tiers] == \
        [n for n, _, _ in TIMELINE_TIERS]


def test_timeline_window_and_dump_jsonl(tmp_path):
    tl = MetricsTimeline(tiers=(("raw", 1.0, 16),))
    tl.add_source("m", lambda: {"v": 1})
    for i in range(6):
        tl.sample_once(t=2000.0 + i)
    win = tl.window(last_s=2.5, t=2005.0)
    assert [w["t"] for w in win] == [2003.0, 2004.0, 2005.0]
    assert win[0]["values"] == {"m.v": 1.0}
    p = tl.dump_jsonl(str(tmp_path / "tl.jsonl"))
    rows = [json.loads(line) for line in open(p)]
    assert len(rows) == 6 and rows[-1] == {"t": 2005.0,
                                           "values": {"m.v": 1.0}}
    with pytest.raises(KeyError):
        tl.dump_jsonl(str(tmp_path / "no.jsonl"), tier="60s")


def test_timeline_broken_source_isolated():
    tl = MetricsTimeline(tiers=(("raw", 1.0, 4),))
    tl.add_source("good", lambda: {"v": 7})
    tl.add_source("bad", lambda: 1 / 0)
    vals = tl.sample_once(t=3000.0)
    assert vals["good.v"] == 7.0
    assert vals["bad.sample_error"] == 1.0 and vals["_errors"] == 1.0
    # the good source's series is intact despite its broken neighbor
    assert tl.query("good.v") == [(3000.0, 7.0)]


def test_timeline_background_sampler_thread():
    tl = MetricsTimeline(tiers=(("raw", 0.0, 64),))
    tl.add_source("s", lambda: {"v": 1})
    th = tl.start(interval_s=0.005)
    assert th is tl.start(interval_s=0.005)      # idempotent
    deadline = time.monotonic() + 5.0
    while tl.samples_taken < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert tl.samples_taken >= 3
    assert tl.snapshot()["sampler_alive"]
    tl.stop()
    assert not tl.snapshot()["sampler_alive"]
    taken = tl.samples_taken
    time.sleep(0.03)
    assert tl.samples_taken == taken             # really stopped


# -------------------------------------------------------------- postmortems

def test_postmortem_capture_refractory_and_force():
    pm = PostmortemStore(max_bundles=2, min_interval_s=60.0)
    pm.add_context("ctx", lambda: {"depth": 3})
    b = pm.capture("ttft_breach_storm", "p50 breached",
                   alarm={"kind": "ttft_breach_storm", "t": 1.0})
    assert b["kind"] == "ttft_breach_storm" and b["seq"] == 0
    assert b["ctx"] == {"depth": 3} and b["alarm"]["t"] == 1.0
    # same kind inside the refractory window: suppressed (counted, None)
    assert pm.capture("ttft_breach_storm", "again") is None
    assert pm.suppressed == 1 and pm.captures == 1
    # a DIFFERENT kind has its own window
    assert pm.capture("eviction_thrash", "thrash")["seq"] == 1
    # force (the on-demand path) bypasses the window
    assert pm.capture("ttft_breach_storm", "forced", force=True)["seq"] == 2
    assert pm.captures == 3
    # ring bound: oldest bundle fell off
    assert [x["seq"] for x in pm.bundles()] == [1, 2]
    assert pm.last()["reason"] == "forced"
    s = pm.summary()
    assert s["captures"] == 3 and s["suppressed"] == 1
    assert s["retained"] == 2 and s["capacity"] == 2
    assert [k["kind"] for k in s["kinds"]] == ["eviction_thrash",
                                               "ttft_breach_storm"]


def test_postmortem_broken_provider_isolated_and_dump(tmp_path):
    pm = PostmortemStore()
    pm.add_context("good", lambda: {"ok": 1})
    pm.add_context("bad", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    b = pm.capture("breaker_open", "replica 0 reaped")
    assert b["good"] == {"ok": 1}
    assert b["bad"] == {"error": "RuntimeError: boom"}
    p = pm.dump(str(tmp_path / "pm.json"))
    rows = json.load(open(p))
    assert len(rows) == 1 and rows[0]["kind"] == "breaker_open"


# ------------------------------------------------------------------ endpoint

def test_endpoint_timeline_and_postmortem_routes():
    tl = MetricsTimeline(tiers=(("raw", 1.0, 8),))
    tl.add_source("src", lambda: {"depth": 4})
    for i in range(3):
        tl.sample_once(t=100.0 + i)
    pm = PostmortemStore()
    pm.add_context("ctx", lambda: {"n": 1})
    pm.capture("stall_storm", "decode stalled")
    ep = ObservabilityEndpoint(include_default_registry=False)
    ep.add_timeline("tl0", tl)
    ep.add_postmortem("pm0", pm)
    ep.start()
    try:
        def get(path):
            return json.loads(urllib.request.urlopen(
                ep.url + path, timeout=10).read().decode())

        idx = get("/debug/timeline")
        assert idx["tl0"]["metrics"] == ["src.depth"]
        assert idx["tl0"]["summary"]["samples_taken"] == 3
        series = get("/debug/timeline?metric=src.depth&last=2")
        assert series["tl0"]["points"] == [[101.0, 4.0], [102.0, 4.0]]
        assert get("/debug/timeline?metric=x&tier=nope")["tl0"]["error"]
        # list-only first: the existing bundle, no on-demand capture
        listed = get("/debug/postmortem?capture=0")
        assert listed["pm0"]["summary"]["captures"] == 1
        assert listed["pm0"]["bundles"][0]["kind"] == "stall_storm"
        # default GET freezes one on-demand bundle per store
        full = get("/debug/postmortem")
        assert full["pm0"]["summary"]["captures"] == 2
        assert full["pm0"]["bundles"][-1]["kind"] == "on_demand"
        assert full["pm0"]["bundles"][-1]["ctx"] == {"n": 1}
        # both routes are discoverable from the index
        routes = get("/debug")["routes"]
        assert "/debug/timeline" in routes and "/debug/postmortem" in routes
    finally:
        ep.stop()
