"""r4 top-level API sweep: paddle.* must cover the reference __init__'s
full __all__ (418 names), with behavioral pins for the newly added ops
(reference python/paddle/tensor/* cited per op)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle

_REF_INIT = "/root/reference/python/paddle/__init__.py"


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree unavailable")
def test_top_level_all_coverage():
    import ast

    names = []
    for node in ast.walk(ast.parse(open(_REF_INIT).read())):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    assert len(names) > 400
    missing = [n for n in names if not hasattr(paddle, n)]
    assert missing == [], missing


def test_block_diag_and_stacks():
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    b = paddle.to_tensor(np.full((1, 3), 2.0, np.float32))
    out = paddle.block_diag([a, b]).numpy()
    assert out.shape == (3, 5)
    np.testing.assert_allclose(out[:2, :2], 1.0)
    np.testing.assert_allclose(out[2, 2:], 2.0)
    assert out[:2, 2:].sum() == 0

    v = [paddle.to_tensor(np.arange(3, dtype=np.float32)) for _ in range(2)]
    assert paddle.hstack(v).shape == [6]
    assert paddle.vstack(v).shape == [2, 3]
    assert paddle.dstack(v).shape == [1, 3, 2]


def test_tensor_split_uneven():
    x = paddle.to_tensor(np.arange(7, dtype=np.int32))
    parts = paddle.tensor_split(x, 3)
    assert [p.shape[0] for p in parts] == [3, 2, 2]
    np.testing.assert_array_equal(parts[0].numpy(), [0, 1, 2])
    parts = paddle.tensor_split(x, [2, 5])
    assert [p.shape[0] for p in parts] == [2, 3, 2]


def test_isin_sgn_signbit_polar():
    x = paddle.to_tensor(np.asarray([1, 3, 5], np.int32))
    t = paddle.to_tensor(np.asarray([3, 5, 9], np.int32))
    np.testing.assert_array_equal(paddle.isin(x, t).numpy(),
                                  [False, True, True])
    np.testing.assert_array_equal(
        paddle.isin(x, t, invert=True).numpy(), [True, False, False])
    np.testing.assert_allclose(
        paddle.sgn(paddle.to_tensor(np.asarray([-2.0, 0.0, 7.0],
                                               np.float32))).numpy(),
        [-1, 0, 1])
    np.testing.assert_array_equal(
        paddle.signbit(paddle.to_tensor(
            np.asarray([-1.0, 0.0, 2.0], np.float32))).numpy(),
        [True, False, False])
    p = paddle.polar(paddle.to_tensor(np.asarray([1.0, 2.0], np.float32)),
                     paddle.to_tensor(np.asarray([0.0, np.pi / 2],
                                                 np.float32)))
    np.testing.assert_allclose(p.numpy(),
                               [1 + 0j, 2j], atol=1e-6)


def test_diagonal_scatter_and_view_as():
    x = paddle.zeros((3, 3))
    y = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
    out = paddle.diagonal_scatter(x, y).numpy()
    np.testing.assert_allclose(np.diag(out), [1, 2, 3])
    v = paddle.view_as(paddle.to_tensor(np.arange(6, dtype=np.float32)),
                       paddle.zeros((2, 3)))
    assert v.shape == [2, 3]


def test_cumulative_trapezoid_and_combinations():
    y = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
    np.testing.assert_allclose(
        paddle.cumulative_trapezoid(y).numpy(), [1.5, 4.0])
    c = paddle.combinations(paddle.to_tensor(
        np.asarray([10, 20, 30], np.int32)), 2)
    np.testing.assert_array_equal(c.numpy(),
                                  [[10, 20], [10, 30], [20, 30]])


def test_histogramdd_and_info():
    pts = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(100, 2)).astype(np.float32))
    hist, edges = paddle.histogramdd(pts, bins=5)
    assert hist.shape == [5, 5] and len(edges) == 2
    assert float(hist.numpy().sum()) == 100.0
    assert paddle.iinfo(paddle.int8).max == 127
    fi = paddle.finfo(paddle.float32)
    assert fi.bits == 32 and fi.eps < 1e-6
    bi = paddle.finfo(paddle.bfloat16)
    assert bi.bits == 16


def test_random_families_reproducible():
    paddle.seed(0)
    lam = paddle.to_tensor(np.full((4,), 5.0, np.float32))
    p1 = paddle.poisson(lam).numpy()
    paddle.seed(0)
    p2 = paddle.poisson(lam).numpy()
    np.testing.assert_array_equal(p1, p2)
    n = paddle.to_tensor(np.full((4,), 10.0, np.float32))
    pr = paddle.to_tensor(np.full((4,), 0.5, np.float32))
    b = paddle.binomial(n, pr).numpy()
    assert ((b >= 0) & (b <= 10)).all()
    g = paddle.standard_gamma(paddle.to_tensor(
        np.full((8,), 2.0, np.float32))).numpy()
    assert (g > 0).all()
    r = paddle.randint_like(paddle.zeros((3, 3)), 2, 9).numpy()
    assert ((r >= 2) & (r < 9)).all()


def test_inplace_variants_and_guard():
    x = paddle.to_tensor(np.asarray([0.5, -0.5], np.float32))
    ret = paddle.tanh_(x)
    assert ret is x
    np.testing.assert_allclose(x.numpy(), np.tanh([0.5, -0.5]), rtol=1e-6)
    # r4-synthesized set: multiply_ / greater_than_ / nan_to_num_
    y = paddle.to_tensor(np.asarray([2.0, 4.0], np.float32))
    paddle.multiply_(y, paddle.to_tensor(np.asarray([3.0, 0.5],
                                                    np.float32)))
    np.testing.assert_allclose(y.numpy(), [6.0, 2.0])
    z = paddle.to_tensor(np.asarray([np.nan, 1.0], np.float32))
    paddle.nan_to_num_(z)
    assert np.isfinite(z.numpy()).all()
    # in-place random fill is seed-reproducible and keeps shape
    paddle.seed(1)
    a = paddle.zeros((64,))
    paddle.normal_(a)
    paddle.seed(1)
    b = paddle.zeros((64,))
    paddle.normal_(b)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    assert abs(float(a.numpy().std()) - 1.0) < 0.35
    # grad-requiring leaves refuse in-place mutation (reference guard)
    w = paddle.to_tensor(np.ones(2, np.float32))
    w.stop_gradient = False
    with pytest.raises(RuntimeError):
        paddle.tanh_(w)


def test_misc_api_names():
    assert int(paddle.rank(paddle.zeros((2, 3, 4))).numpy()) == 3
    p = paddle.create_parameter([4, 2], "float32")
    assert p.shape == [4, 2] and not p.stop_gradient
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert paddle.is_floating_point(paddle.zeros((1,)))
    assert not paddle.is_integer(paddle.zeros((1,)))
    with paddle.LazyGuard():
        import paddle_tpu.nn as nn

        lin = nn.Linear(2, 2)
    assert lin.weight.shape == [2, 2]
    from paddle_tpu import nn as nn2

    net = nn2.Sequential(nn2.Linear(8, 4), nn2.ReLU(), nn2.Linear(4, 2))
    fl = paddle.flops(net, [1, 8])
    assert fl == 8 * 4 + 4 * 2
    with pytest.raises(RuntimeError):
        paddle.CUDAPlace(0)
    paddle.set_printoptions(precision=4)
    paddle.disable_signal_handler()
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)


@pytest.mark.skipif(not os.path.exists(_REF_INIT),
                    reason="reference tree unavailable")
def test_subnamespace_all_coverage():
    """optimizer/distributed/io/amp/jit/metric/nn __all__ parity."""
    import ast
    import importlib

    def allnames(path):
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return [ast.literal_eval(e) for e in node.value.elts]
        return []

    ref_root = "/root/reference/python/paddle"
    for sub, mod in [("optimizer", "paddle_tpu.optimizer"),
                     ("distributed", "paddle_tpu.distributed"),
                     ("io", "paddle_tpu.io"),
                     ("amp", "paddle_tpu.amp"),
                     ("jit", "paddle_tpu.jit"),
                     ("metric", "paddle_tpu.metric"),
                     ("nn", "paddle_tpu.nn")]:
        names = allnames(f"{ref_root}/{sub}/__init__.py")
        m = importlib.import_module(mod)
        missing = [n for n in names if not hasattr(m, n)]
        assert missing == [], (sub, missing)


def test_extra_optimizers_converge():
    from paddle_tpu import nn, optimizer as opt

    rng = np.random.default_rng(0)
    X = paddle.to_tensor(rng.normal(size=(32, 6)).astype(np.float32))
    W = rng.normal(size=(6, 1)).astype(np.float32)
    Y = paddle.to_tensor((np.asarray(X.numpy()) @ W).astype(np.float32))
    mse = nn.MSELoss()

    for name, lr in [("Adadelta", 1.0), ("ASGD", 0.05), ("Rprop", 0.05),
                     ("RAdam", 0.05), ("NAdam", 0.05)]:
        paddle.seed(0)
        lin = nn.Linear(6, 1)
        o = getattr(opt, name)(learning_rate=lr,
                               parameters=lin.parameters())
        first = last = None
        for _ in range(30):
            loss = mse(lin(X), Y)
            loss.backward()
            o.step()
            o.clear_grad()
            v = float(np.asarray(loss.numpy()))
            first = first if first is not None else v
            last = v
        assert last < first, (name, first, last)

    # LBFGS closure mode converges hard on the quadratic
    paddle.seed(0)
    lin = nn.Linear(6, 1)
    o = opt.LBFGS(learning_rate=0.5, max_iter=10,
                  parameters=lin.parameters())

    def closure():
        o.clear_grad()
        loss = mse(lin(X), Y)
        loss.backward()
        return loss

    l0 = float(np.asarray(closure().numpy()))
    for _ in range(3):
        o.step(closure)
    l1 = float(np.asarray(mse(lin(X), Y).numpy()))
    assert l1 < l0 * 0.01, (l0, l1)
    with pytest.raises(NotImplementedError):
        opt.LBFGS(parameters=nn.Linear(2, 2).parameters(),
                  line_search_fn="strong_wolfe")


def test_distributed_api_surface():
    import paddle_tpu.distributed as dist

    assert dist.is_available() and dist.get_backend() == "XCCL"
    assert dist.ParallelMode.TENSOR_PARALLEL == 1
    assert dist.ReduceType.kRedSum == 0
    with pytest.raises(NotImplementedError):
        dist.split(None, (4, 4), "linear")
    with pytest.raises(NotImplementedError):
        dist.InMemoryDataset()
    s = dist.Strategy()
    s.hybrid_configs = {"dp_degree": 2}
    assert s.hybrid_configs["dp_degree"] == 2
    a = dist.DistAttr(mesh=None, sharding_specs=["x", None])
    assert a.sharding_specs == ["x", None]
    # unshard returns a dense host-backed tensor
    t = paddle.to_tensor(np.arange(6, dtype=np.float32))
    d = dist.unshard_dtensor(t)
    np.testing.assert_allclose(np.asarray(d.numpy()),
                               np.arange(6, dtype=np.float32))
    # shard_optimizer hooks state creation
    from paddle_tpu import nn, optimizer as opt

    lin = nn.Linear(2, 2)
    o = dist.shard_optimizer(opt.Adam(parameters=lin.parameters()),
                             shard_fn=lambda k, p, v: v)
    loss = lin(paddle.to_tensor(np.ones((1, 2), np.float32))).sum()
    loss.backward()
    o.step()


def test_distributed_object_collectives_world1():
    import paddle_tpu.distributed as dist

    objs = [{"a": 1, "b": [2, 3]}]
    dist.broadcast_object_list(objs, src=0)
    assert objs == [{"a": 1, "b": [2, 3]}]
    out = []
    dist.scatter_object_list(out, [("x", 7)], src=0)
    assert out == [("x", 7)]
    import jax

    world = jax.device_count() if True else 1
    from paddle_tpu.distributed.env import get_world_size

    world = get_world_size()
    g = []
    # stacked [world, rows] convention of the single-controller mode
    stacked = paddle.to_tensor(
        np.arange(world * 4, dtype=np.float32).reshape(world, 4))
    dist.gather(stacked, g, dst=0)
    assert len(g) == world
    np.testing.assert_allclose(np.asarray(g[1].numpy()),
                               np.arange(4, 8, dtype=np.float32))
    o = paddle.zeros((world, world))
    sq = paddle.to_tensor(np.arange(world * world,
                                    dtype=np.float32).reshape(world, world))
    dist.alltoall_single(o, sq)
    # all-to-all of the stacked square = its block transpose
    np.testing.assert_allclose(np.asarray(o.numpy()),
                               np.asarray(sq.numpy()).T)


def test_worker_info_inside_dataloader():
    import paddle_tpu.io as io

    assert io.get_worker_info() is None
    seen = []

    class DS(io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = io.get_worker_info()
            seen.append(None if info is None else (info.id,
                                                   info.num_workers))
            return np.float32(i)

    loader = io.DataLoader(DS(), batch_size=2, num_workers=2)
    _ = [b for b in loader]
    worker_seen = [s for s in seen if s is not None]
    assert worker_seen and all(nw == 2 and wid in (0, 1)
                               for wid, nw in worker_seen)


def test_lbfgs_history_builds():
    from paddle_tpu import nn, optimizer as opt

    rng = np.random.default_rng(0)
    X = paddle.to_tensor(rng.normal(size=(16, 3)).astype(np.float32))
    Y = paddle.to_tensor(rng.normal(size=(16, 1)).astype(np.float32))
    mse = nn.MSELoss()
    lin = nn.Linear(3, 1)
    o = opt.LBFGS(learning_rate=0.3, max_iter=6,
                  parameters=lin.parameters())

    def closure():
        o.clear_grad()
        loss = mse(lin(X), Y)
        loss.backward()
        return loss

    o.step(closure)
    # the curvature history must actually accumulate (a zero s-vector
    # from storing the post-step point would keep it empty forever)
    assert len(o._s) > 0


def test_enable_to_static_layer_method():
    import paddle_tpu.jit as jit
    from paddle_tpu import nn

    lin = nn.Linear(2, 2)
    wrapped = jit.to_static(lin)
    x = paddle.to_tensor(np.ones((1, 2), np.float32))
    ref = np.asarray(wrapped(x).numpy())
    jit.enable_to_static(False)
    try:
        out = np.asarray(wrapped(x).numpy())  # bound-method eager path
    finally:
        jit.enable_to_static(True)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
