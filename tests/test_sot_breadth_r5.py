"""r5 SOT breadth (VERDICT r4 missing #5): new opcode handlers (sets,
dict merges, f-strings, starred unpack/call, MAKE_FUNCTION) through the
bytecode tier, plus the PEP-523 eval-frame discovery entry (detection
mode; reference eval_frame.c:439)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.sot import sot_stats, symbolic_translate


def t(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


def _check(fn, *args):
    eager = fn(*args)
    wrapped = symbolic_translate(fn)
    got = wrapped(*args)
    np.testing.assert_allclose(np.asarray(got.numpy()),
                               np.asarray(eager.numpy()), rtol=1e-5)
    return wrapped


def test_build_set_and_update():
    def f(x):
        axes = {0}
        axes.add(1)
        axes.update({1, 0})
        return paddle.sum(x) * len(axes)

    w = _check(f, t([1.0, 2.0]))
    assert sot_stats(w)["bytecode"]


def test_dict_merge_and_const_key_map():
    def f(x):
        base = {"a": 1.0, "b": 2.0}
        extra = {"c": 3.0}
        merged = {**base, **extra}
        return x * sum(merged.values())

    w = _check(f, t([1.0]))
    assert sot_stats(w)["bytecode"]


def test_dict_comprehension_map_add():
    def f(x):
        scales = {i: float(i + 1) for i in range(3)}
        return x * scales[2]

    w = _check(f, t([2.0]))
    assert sot_stats(w)["bytecode"]


def test_fstring_on_python_values():
    def f(x, n=3):
        label = f"scale_{n}x"
        return x * float(len(label))

    w = _check(f, t([1.0]))
    assert sot_stats(w)["bytecode"]


def test_unpack_ex():
    def f(x):
        first, *rest = [1.0, 2.0, 3.0, 4.0]
        return x * (first + rest[-1])

    w = _check(f, t([1.0]))
    assert sot_stats(w)["bytecode"]


def test_call_function_ex_star_args():
    def f(x):
        args = (x, x)
        kw = {"y": 2.0}

        def g(a, b, y=1.0):
            return a + b * y

        return paddle.sum(g(*args, **kw))

    # inner def needs MAKE_FUNCTION + CALL_FUNCTION_EX
    w = _check(f, t([1.0, 2.0]))
    assert sot_stats(w)["bytecode"]


def test_make_function_with_defaults():
    def f(x):
        def scale(v, k=3.0):
            return v * k

        return paddle.sum(scale(x))

    w = _check(f, t([1.0, 2.0]))
    assert sot_stats(w)["bytecode"]


class TestEvalFrameEntry:
    def test_capture_patches_all_references(self):
        from paddle_tpu.jit.sot import eval_frame as ef

        def fn(x):
            return paddle.sum(x * 2.0)

        alias = fn
        x = t([1.0, 2.0, 3.0])
        eager = float(fn(x).numpy())
        assert ef.capture(fn)
        try:
            got = float(alias(x).numpy())  # pre-capture alias
            assert abs(got - eager) < 1e-5
            st = ef.sot_stats_of(fn)
            assert st is not None and st["bytecode"]
        finally:
            assert ef.release(fn)
        # released: original code restored
        assert ef.sot_stats_of(fn) is None
        assert abs(float(fn(x).numpy()) - eager) < 1e-5

    def test_capture_declines_closures(self):
        from paddle_tpu.jit.sot import eval_frame as ef

        k = 2.0

        def fn(x):
            return x * k

        assert not ef.capture(fn)

    def test_pep523_discovery_hook(self):
        from paddle_tpu.jit.sot import eval_frame as ef

        ext = ef._build_ext()
        if ext is None:
            pytest.skip(f"extension unavailable: {ef.build_error()}")

        def auto(x):
            return paddle.mean(x + 1.0)

        x = t([1.0, 3.0])
        try:
            assert ef.enable(watch=[auto])
            assert ext.installed()
            v1 = float(auto(x).numpy())   # detection call (eager)
            assert any(f is auto for f, _ in ef._PATCHED.values())
            v2 = float(auto(x).numpy())   # routed through SOT
            assert abs(v1 - v2) < 1e-6
            st = ef.sot_stats_of(auto)
            assert st is not None
        finally:
            ef.disable()
            ef.release(auto)
        assert not ext.installed()
