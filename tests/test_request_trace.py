"""Request-lifecycle observability: per-request tracing, labeled metrics,
serving host-stall attribution, flight recorder + alarms, SLO/goodput
accounting, and the live /metrics + /debug/requests endpoint.

Correctness bar: phase durations partition E2E latency EXACTLY (gapless
same-timestamp transitions), the token stream is bit-identical with
observability on vs off (tracing observes the host timeline, never the
model), and full instrumentation stays under the 5% overhead budget.
"""

import json
import time
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability import (
    FlightRecorder,
    MetricsRegistry,
    ObservabilityEndpoint,
    RequestTracer,
    ServingStall,
    TTFTBreachStorm,
    parse_prometheus_text,
)
from paddle_tpu.observability.request_trace import (
    PHASE_ADMIT,
    PHASE_PREEMPTED,
    PHASE_QUEUED,
    PHASE_RUNNING,
)
from paddle_tpu.observability.serving_stall import (
    AlarmMonitors,
    EvictionThrash,
    STALL_PHASES,
)


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """Serving decode programs compile fresh (XLA:CPU AOT replay corrupts
    their numerics — same guard as test_serving_sched)."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    paddle.seed(7)
    return GPTForCausalLM(gpt_tiny(num_layers=1))


# ------------------------------------------------------- labeled metrics

def test_counter_gauge_labels_exposition_round_trip():
    reg = MetricsRegistry(namespace="t")
    fam = reg.counter("stall_seconds", "stall by phase")
    fam.labels(phase="admission").inc(0.25)
    fam.labels(phase="streaming").inc(0.5)
    # same label set -> the SAME child
    fam.labels(phase="admission").inc(0.25)
    g = reg.gauge("depth")
    g.labels(queue="high").set(3)
    text = reg.prometheus_text()
    assert 't_stall_seconds{phase="admission"} 0.5' in text
    assert 't_stall_seconds{phase="streaming"} 0.5' in text
    assert 't_depth{queue="high"} 3' in text
    parsed = parse_prometheus_text(text)
    assert parsed["t_stall_seconds"]["series"] == {
        'phase="admission"': 0.5, 'phase="streaming"': 0.5}
    assert ({"phase": "admission"}, 0.5) in parsed["t_stall_seconds"][
        "labeled"]
    # snapshot carries labeled children under name{k="v"} keys
    snap = reg.snapshot()
    assert snap['t_stall_seconds{phase="admission"}'] == 0.5
    # untouched parent of a labeled family is suppressed from exposition
    assert "\nt_stall_seconds 0" not in text
    # children are counters too: monotonic
    with pytest.raises(ValueError):
        fam.labels(phase="admission").inc(-1)
    with pytest.raises(ValueError):
        fam.labels(phase="admission").labels(x="y")   # no nested labels


def test_unlabeled_metrics_exposition_unchanged():
    reg = MetricsRegistry()
    reg.counter("events_total").inc(3)
    text = reg.prometheus_text()
    assert "events_total 3" in text
    assert parse_prometheus_text(text)["events_total"]["value"] == 3


# -------------------------------------------------------- request traces

def test_request_trace_phases_partition_e2e_exactly():
    tracer = RequestTracer()
    tr = tracer.start(7, t=100.0, prompt_tokens=5)
    tr.transition(PHASE_ADMIT, t=100.5)
    tr.subspan("prefill", 0.2)          # nested: excluded from partition
    tr.transition(PHASE_RUNNING, t=101.0)
    tr.transition(PHASE_PREEMPTED, t=101.25)
    tr.transition(PHASE_ADMIT, t=101.5)
    tr.transition(PHASE_RUNNING, t=102.0)
    tracer.finish(7, t=103.0)
    tr = tracer.completed()[0]
    d = tr.phase_durations()
    assert d == {PHASE_QUEUED: 0.5, PHASE_ADMIT: 1.0,
                 PHASE_RUNNING: 1.25, PHASE_PREEMPTED: 0.25}
    assert sum(d.values()) == pytest.approx(tr.e2e_s())
    assert tr.e2e_s() == 3.0
    assert tr.phase_count(PHASE_ADMIT) == 2
    dd = tr.to_dict()
    assert dd["subspans"]["prefill"] == {"calls": 1, "total_s": 0.2}
    assert dd["request_id"] == 7 and dd["prompt_tokens"] == 5


def test_tracer_ring_bound_and_disabled_noop():
    tracer = RequestTracer(max_completed=2)
    for rid in range(4):
        tracer.start(rid)
        tracer.finish(rid)
    assert [t.request_id for t in tracer.completed()] == [2, 3]
    off = RequestTracer(enabled=False)
    assert off.start(0) is None and off.get(0) is None
    off.finish(0)                        # harmless
    assert off.to_json() == []


def test_chrome_trace_one_track_per_request():
    tracer = RequestTracer()
    for rid in (3, 9):
        tr = tracer.start(rid, t=0.0)
        tr.transition(PHASE_ADMIT, t=0.1)
        tr.event("resumed", t=0.15)
        tr.transition(PHASE_RUNNING, t=0.2)
        tracer.finish(rid, t=0.3)
    ct = tracer.chrome_trace()
    by_tid = {}
    for e in ct["traceEvents"]:
        if e["ph"] != "M" or e["name"] == "thread_name":
            by_tid.setdefault(e["tid"], []).append(e)
    assert set(by_tid) == {3, 9}
    names = {e["name"] for e in by_tid[3]}
    assert {"req.queued", "req.admit", "req.running",
            "req.resumed"} <= names
    span = next(e for e in by_tid[3] if e["name"] == "req.admit")
    assert span["ph"] == "X" and span["dur"] > 0


# ------------------------------------------------- stall + flight + alarms

def test_serving_stall_breakdown_and_prometheus_face():
    reg = MetricsRegistry(namespace="serving")
    st = ServingStall(reg)
    st.record("admission", 0.1)
    with st.timed("sampling_sync"):
        time.sleep(0.002)
    snap = st.snapshot()
    assert set(snap) == set(STALL_PHASES) | {"total"}
    assert snap["admission"] == pytest.approx(0.1)
    assert snap["sampling_sync"] >= 0.002
    assert snap["total"] == pytest.approx(
        sum(snap[p] for p in STALL_PHASES))
    assert 'serving_host_stall_seconds{phase="admission"}' \
        in reg.prometheus_text()
    with pytest.raises(KeyError):
        st.record("nope", 1.0)
    # default-registry flavor gets the serving_ prefix
    st2 = ServingStall()
    st2.record("streaming", 0.0)
    from paddle_tpu.observability import get_registry

    assert any(k.startswith("serving_host_stall_seconds")
               for k in get_registry().snapshot())


def test_flight_recorder_ring_and_alarm_freeze():
    fr = FlightRecorder(max_steps=3)
    for i in range(5):
        fr.record_step(queue_depth=i)
    dump = fr.dump()
    assert len(dump) == 3
    assert [r["step"] for r in dump] == [3, 4, 5]
    assert fr.steps_recorded == 5
    assert fr.dump(last=1)[0]["queue_depth"] == 4
    fr.alarm("test_alarm", "because")
    fr.record_step(queue_depth=9)        # ring rolls on...
    assert fr.last_alarm_dump["kind"] == "test_alarm"
    # ...but the frozen dump kept the incident window
    assert [r["step"] for r in fr.last_alarm_dump["steps"]] == [3, 4, 5]


def test_ttft_breach_storm_and_eviction_thrash_alarms():
    fr = FlightRecorder(8)
    mon = AlarmMonitors(fr, ttft_streak=3, thrash_window=4, thrash_frac=0.5)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mon.observe_ttft(True, 0.9, 0.1)
        mon.observe_ttft(False, 0.05, 0.1)   # streak resets
        mon.observe_ttft(True, 0.9, 0.1)
        mon.observe_ttft(True, 0.9, 0.1)
        assert not any(isinstance(x.message, TTFTBreachStorm) for x in w)
        mon.observe_ttft(True, 0.9, 0.1)     # third consecutive -> storm
    assert any(isinstance(x.message, TTFTBreachStorm) for x in w)
    assert fr.last_alarm_dump["kind"] == "ttft_breach_storm"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(4):
            mon.observe_evictions(2)
    assert any(isinstance(x.message, EvictionThrash) for x in w)


# --------------------------------------------------------- SLO / goodput

def _fake_req_out(ttft, tpot, n_tokens, preemptions=0):
    class Out:
        ttft_s, tpot_s = ttft, tpot
        generated_ids = np.arange(n_tokens)

    class Req:
        num_preemptions = preemptions

    return Req(), Out()


def test_slo_breach_attribution_and_goodput():
    from paddle_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics(ttft_slo_s=0.1, tpot_slo_s=0.05)
    tracer = RequestTracer()
    # queue-dominated TTFT breach
    tr = tracer.start(0, t=0.0)
    tr.transition(PHASE_ADMIT, t=0.4)        # 0.4s queued
    tr.transition(PHASE_RUNNING, t=0.45)     # 0.05s admit
    tracer.finish(0, t=0.6)
    req, out = _fake_req_out(0.45, 0.01, 10)
    v = m.observe_slo(req, out, trace=tracer.get(0))
    assert v["ttft_breach"] and v["ttft_cause"] == "queue_wait"
    assert not v["tpot_breach"]
    # prefill-dominated TTFT breach
    tr = tracer.start(1, t=0.0)
    tr.transition(PHASE_ADMIT, t=0.01)
    tr.transition(PHASE_RUNNING, t=0.3)      # 0.29s admit (prefill)
    tracer.finish(1, t=0.4)
    req, out = _fake_req_out(0.3, 0.01, 10)
    v = m.observe_slo(req, out, trace=tracer.get(1))
    assert v["ttft_breach"] and v["ttft_cause"] == "prefill"
    # TPOT breach attributed to preemption
    req, out = _fake_req_out(0.05, 0.2, 10, preemptions=1)
    v = m.observe_slo(req, out, trace=None)
    assert v["tpot_breach"] and v["tpot_cause"] == "preemption"
    # a compliant request earns goodput
    req, out = _fake_req_out(0.05, 0.01, 10)
    v = m.observe_slo(req, out)
    assert not v["ttft_breach"] and not v["tpot_breach"]
    snap = m.slo_snapshot()
    assert snap["judged_tokens"] == 40 and snap["goodput_tokens"] == 10
    assert snap["goodput_ratio"] == pytest.approx(0.25)
    assert snap["breaches"]['cause="queue_wait",kind="ttft"'] == 1
    assert snap["breaches"]['cause="prefill",kind="ttft"'] == 1
    assert snap["breaches"]['cause="preemption",kind="tpot"'] == 1
    prom = parse_prometheus_text(m.prometheus_text())
    assert prom["serving_slo_breach_total"]["series"][
        'cause="queue_wait",kind="ttft"'] == 1
    assert prom["serving_goodput_ratio"]["value"] == pytest.approx(0.25)


# ------------------------------------------- scheduler integration (e2e)

def _run(model, prompts, max_new, **cfg_kw):
    from paddle_tpu.serving import ContinuousBatchingScheduler, \
        SchedulerConfig

    cfg = SchedulerConfig(**cfg_kw)
    sched = ContinuousBatchingScheduler(model, cfg)
    outs = sched.generate(prompts, max_new_tokens=max_new)
    return sched, outs


def test_lifecycle_spans_across_preempt_resume(model):
    """Forced preemption: the victim's trace carries queued -> admit ->
    running -> preempted -> admit(resume) -> running -> done, phase
    durations sum to its measured E2E latency, and tokens are identical
    with tracing off."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1000, 10), rng.integers(0, 1000, 9)]
    kw = dict(max_num_seqs=2, max_seq_len=64, block_size=4, num_blocks=6,
              max_new_tokens=8)
    sched, outs = _run(model, prompts, 8, enable_request_tracing=True, **kw)
    assert sched.metrics.preemptions >= 1
    traces = {t.request_id: t for t in sched.tracer.completed()}
    assert len(traces) == 2
    victim = next(t for t in traces.values()
                  if t.phase_count(PHASE_PREEMPTED) >= 1)
    phases = [p for p, _, _ in victim.phases]
    assert phases[0] == PHASE_QUEUED
    assert PHASE_PREEMPTED in phases
    assert phases.index(PHASE_PREEMPTED) < len(phases) - 1
    # resumed: a second admit AFTER the preemption
    assert victim.phase_count(PHASE_ADMIT) >= 2
    assert any(n == "resumed" for n, _, _ in victim.events)
    for tr in traces.values():
        d = tr.phase_durations()
        assert sum(d.values()) == pytest.approx(tr.e2e_s(), abs=1e-9)
        assert tr.meta["finish_reason"] in ("eos", "length")
    # token identity: tracing off produces the same streams
    sched_off, outs_off = _run(model, prompts, 8,
                               enable_request_tracing=False, **kw)
    assert sched_off.tracer.completed() == []
    for a, b in zip(outs, outs_off):
        np.testing.assert_array_equal(a, b)


def test_prefix_cache_hit_admission_traced(model):
    """A radix-tree hit shows up in the request's trace: cached_tokens
    noted, prefix_hit event, radix_match sub-span recorded."""
    from paddle_tpu.serving import ContinuousBatchingScheduler, \
        SchedulerConfig

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 1000, 32)
    cfg = SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8,
                          enable_prefix_caching=True)
    sched = ContinuousBatchingScheduler(model, cfg)
    sched.add_request(prompt, max_new_tokens=4)
    while sched.has_unfinished():
        sched.step()
    rid2 = sched.add_request(prompt, max_new_tokens=4)   # full-prefix hit
    while sched.has_unfinished():
        sched.step()
    tr = sched.tracer.get(rid2)
    assert tr.meta["cached_tokens"] > 0
    assert tr.meta["prefilled_tokens"] + tr.meta["cached_tokens"] \
        == len(prompt)
    assert "prefix_match" in tr.subspans and "prefill" in tr.subspans
    assert sched.stall.seconds("radix_match") > 0
    d = tr.phase_durations()
    assert sum(d.values()) == pytest.approx(tr.e2e_s(), abs=1e-9)


def test_stall_breakdown_populated_by_serving(model):
    rng = np.random.default_rng(0)
    sched, _ = _run(model, [rng.integers(0, 1000, 8) for _ in range(3)], 6,
                    max_num_seqs=2, max_seq_len=64, block_size=8)
    snap = sched.stall.snapshot()
    for phase in ("admission", "block_accounting", "streaming",
                  "sampling_sync"):
        assert snap[phase] > 0, (phase, snap)
    assert snap["total"] < 1.0          # bookkeeping, not seconds of work
    # the breakdown rides the scheduler's ServingMetrics prometheus text
    prom = sched.metrics.prometheus_text()
    assert 'serving_host_stall_seconds{phase="sampling_sync"}' in prom
    # flight recorder saw every iteration
    assert sched.flight.steps_recorded > 0
    row = sched.flight.dump(last=1)[0]
    assert {"running", "queue_depth", "free_blocks", "prefill_tokens",
            "generated_tokens", "preemptions"} <= set(row)


def test_endpoint_serves_live_scheduler(model):
    rng = np.random.default_rng(5)
    from paddle_tpu.serving import ContinuousBatchingScheduler, \
        SchedulerConfig

    sched = ContinuousBatchingScheduler(model, SchedulerConfig(
        max_num_seqs=2, max_seq_len=64, block_size=8,
        ttft_slo_s=10.0, tpot_slo_s=10.0))
    for _ in range(3):
        sched.add_request(rng.integers(0, 1000, 8), max_new_tokens=4)
    ep = sched.start_endpoint()
    try:
        sched.step()                     # some live, some queued
        dbg = json.loads(urllib.request.urlopen(
            ep.url + "/debug/requests", timeout=10).read().decode())
        s0 = dbg["scheduler0"]
        states = {r["state"] for r in s0["requests"]}
        assert "RUNNING" in states and len(s0["requests"]) == 3
        assert set(s0["stall_seconds"]) == set(STALL_PHASES) | {"total"}
        while sched.has_unfinished():
            sched.step()
        text = urllib.request.urlopen(
            ep.url + "/metrics", timeout=10).read().decode()
        prom = parse_prometheus_text(text)
        assert prom["serving_requests_finished"]["value"] == 3
        assert 'serving_host_stall_seconds{phase="admission"}' in text
        assert prom["serving_goodput_ratio"]["value"] == 1.0
        # process-wide default registry rides the same page
        assert "compiles_total" in prom
        dbg = json.loads(urllib.request.urlopen(
            ep.url + "/debug/requests?last=2", timeout=10).read().decode())
        assert len(dbg["scheduler0"]["flight_recorder"]) == 2
        assert len(dbg["scheduler0"]["traces"]["completed"]) == 3
        # liveness + 404 routing
        assert urllib.request.urlopen(
            ep.url + "/healthz", timeout=10).read() == b"ok"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(ep.url + "/nope", timeout=10)
    finally:
        ep.stop()


def test_ttft_breach_storm_fires_on_scheduler(model):
    rng = np.random.default_rng(2)
    from paddle_tpu.serving import ContinuousBatchingScheduler, \
        SchedulerConfig

    sched = ContinuousBatchingScheduler(model, SchedulerConfig(
        max_num_seqs=2, max_seq_len=64, block_size=8,
        ttft_slo_s=1e-9, ttft_breach_streak=3))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(4):
            sched.add_request(rng.integers(0, 1000, 6), max_new_tokens=3)
        while sched.has_unfinished():
            sched.step()
    assert any(isinstance(x.message, TTFTBreachStorm) for x in w)
    assert sched.flight.last_alarm_dump["kind"] == "ttft_breach_storm"
    assert sched.metrics.slo_snapshot()["goodput_ratio"] == 0.0
    assert sum(v for v in sched.metrics.slo_snapshot()["breaches"]
               .values()) >= 4


def test_export_request_trace_chrome_artifact(model, tmp_path):
    rng = np.random.default_rng(4)
    sched, _ = _run(model, [rng.integers(0, 1000, 8)], 4,
                    max_num_seqs=2, max_seq_len=64, block_size=8)
    path = str(tmp_path / "reqtrace.json")
    sched.export_request_trace(path)
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"req.queued", "req.admit", "req.running"} <= names
    # profiler export_report folds the same timelines in
    import paddle_tpu.profiler as prof

    with prof.Profiler(timer_only=False) as p:
        pass
    rep = p.export_report(request_tracers=[sched.tracer])
    assert rep["request_traces"][0][0]["phase_totals_s"]


# ------------------------------------------------------ overhead budget

def test_full_observability_overhead_and_token_identity():
    """The tier-1 face of the <5% budget: deterministic unit-cost
    attribution of every observability primitive against the smoke run's
    wall, plus the hard guarantee — token streams identical on vs off."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(repo, "tools", "serve_bench.py"))
    sb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sb)
    res = sb.measure_tracing_overhead(repeats=1)
    assert res["token_identical"], res["outputs_sha1"]
    assert res["attributed_overhead_pct"] < 5.0, res


# ---------------------------------------------- live export + failover resume

def test_live_trace_export_includes_open_final_span():
    """A trace exported mid-flight (postmortem taken during an incident)
    shows the still-open phase up to "now" — not a timeline that appears
    to stop at the last transition."""
    tracer = RequestTracer()
    t0 = time.perf_counter()
    tr = tracer.start(42, t=t0 - 1.0, prompt_tokens=3)
    tr.transition(PHASE_ADMIT, t=t0 - 0.5)
    tr.transition(PHASE_RUNNING, t=t0 - 0.25)
    d = tr.to_dict()
    assert d["finish_t"] is None and d["phase"] == PHASE_RUNNING
    open_rows = [r for r in d["phases"] if r.get("open")]
    assert len(open_rows) == 1
    assert open_rows[0]["phase"] == PHASE_RUNNING
    assert open_rows[0]["t0"] == pytest.approx(t0 - 0.25)
    assert open_rows[0]["dur_s"] >= 0.25
    # the open remainder is folded into the totals, so the totals cover
    # the full arrival->now window even though the request hasn't finished
    assert sum(d["phase_totals_s"].values()) >= 1.0
    # closed rows never carry the marker
    assert all("open" not in r for r in d["phases"] if r is not open_rows[0])
    # to_json(include_live=True) carries the same synthesized row
    rows = tracer.to_json()
    assert any(r.get("open") for r in rows[-1]["phases"])
    # chrome_trace renders the live request with an open final X span
    ct = tracer.chrome_trace()
    live_spans = [e for e in ct["traceEvents"]
                  if e.get("tid") == 42 and e.get("ph") == "X"
                  and e.get("args", {}).get("open")]
    assert len(live_spans) == 1
    assert live_spans[0]["name"] == "req.running"
    assert live_spans[0]["dur"] > 0


def test_export_snapshot_resume_failover_gapless():
    """The cross-replica half of "one request = one timeline": a snapshot
    exported off a dead replica, resumed on a survivor, yields ONE trace
    whose phases still partition E2E exactly — with an explicit gapless
    ``failover`` phase bridging export -> import."""
    from paddle_tpu.observability.request_trace import PHASE_FAILOVER

    dead = RequestTracer()
    tr = dead.start(5, t=100.0, prompt_tokens=4, priority=1)
    tr.transition(PHASE_ADMIT, t=100.5)
    tr.subspan("prefill", 0.2)
    tr.transition(PHASE_RUNNING, t=101.0)
    tr.event("resumed", t=101.1)
    snap = dead.export_snapshot(5, t=101.5)
    assert snap is not None and snap["export_t"] == 101.5
    assert snap["open_phase"] == PHASE_RUNNING
    # the export REMOVED the trace from the dead tracer
    assert dead.get(5) is None and dead.live() == []

    survivor = RequestTracer()
    tr2 = survivor.resume(9, snap, t=102.0, replica_hop=1)
    assert survivor.get(9) is tr2
    # prior history survived the hop
    assert tr2.arrival_t == 100.0
    assert tr2.phase_count(PHASE_ADMIT) == 1
    assert tr2.subspans["prefill"] == [1, 0.2]
    assert any(n == "resumed" for n, _, _ in tr2.events)
    # failover phase bridges export -> import exactly
    fo = [(p, t0, t1) for p, t0, t1 in tr2.phases if p == PHASE_FAILOVER]
    assert fo == [(PHASE_FAILOVER, 101.5, 102.0)]
    # resumed request re-enters the survivor's queue
    assert tr2.current_phase == PHASE_QUEUED
    tr2.transition(PHASE_ADMIT, t=102.5)
    tr2.transition(PHASE_RUNNING, t=103.0)
    survivor.finish(9, t=104.0)
    done = survivor.completed()[0]
    d = done.phase_durations()
    assert d[PHASE_FAILOVER] == 0.5
    assert sum(d.values()) == pytest.approx(done.e2e_s(), abs=1e-9)
    assert done.e2e_s() == 4.0


def test_resume_without_snapshot_falls_back_to_start():
    survivor = RequestTracer()
    tr = survivor.resume(3, None, t=50.0, prompt_tokens=2)
    assert tr is not None and tr.arrival_t == 50.0
    assert tr.current_phase == PHASE_QUEUED
    assert tr.phase_count("failover") == 0
    off = RequestTracer(enabled=False)
    assert off.resume(3, {"arrival_t": 0.0}) is None
    assert off.export_snapshot(3) is None
