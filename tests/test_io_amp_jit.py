"""DataLoader / save-load / AMP / jit.to_static tests."""

import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.io import DataLoader, Dataset, TensorDataset


class _Sq(Dataset):
    def __len__(self):
        return 10

    def __getitem__(self, i):
        return np.float32(i), np.float32(i * i)


def test_dataloader_batches():
    dl = DataLoader(_Sq(), batch_size=4, shuffle=False, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    x, y = batches[0]
    np.testing.assert_allclose(np.asarray(x.numpy()).ravel(), [0, 1, 2, 3])
    np.testing.assert_allclose(np.asarray(y.numpy()).ravel(), [0, 1, 4, 9])


def test_dataloader_shuffle_epoch():
    dl = DataLoader(_Sq(), batch_size=10, shuffle=True)
    (x, _), = list(dl)
    assert sorted(np.asarray(x.numpy()).ravel().tolist()) == list(range(10))


def test_tensor_dataset():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32))
    ds = TensorDataset([a])
    assert len(ds) == 6


def test_save_load_state_dict():
    m = nn.Linear(3, 3)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.pdparams")
        paddle.save(m.state_dict(), path)
        loaded = paddle.load(path)
        m2 = nn.Linear(3, 3)
        m2.set_state_dict(loaded)
        np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_amp_autocast_low_precision_matmul():
    with paddle.amp.auto_cast(level="O1"):
        a = paddle.to_tensor(np.ones((4, 4), dtype=np.float32))
        b = paddle.to_tensor(np.ones((4, 4), dtype=np.float32))
        c = paddle.matmul(a, b)
    assert c.dtype in (paddle.bfloat16, paddle.float16), c.dtype


def test_amp_blacklist_stays_fp32():
    with paddle.amp.auto_cast(level="O1"):
        x = paddle.to_tensor(np.ones((4,), dtype=np.float32))
        s = F.softmax(x)
    assert s.dtype == paddle.float32


def test_grad_scaler_roundtrip():
    m = nn.Linear(2, 1)
    optim = paddle.optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
    x = paddle.to_tensor(np.ones((4, 2), dtype=np.float32))
    loss = paddle.sum(m(x))
    scaled = scaler.scale(loss)
    scaled.backward()
    before = m.weight.numpy().copy()
    scaler.step(optim)
    scaler.update()
    optim.clear_grad()
    after = m.weight.numpy()
    # step happened, and with UNSCALED gradient (grad of sum over 4 rows = 4)
    np.testing.assert_allclose(before - after, 0.01 * 4 * np.ones_like(before), rtol=1e-5)


def test_to_static_matches_eager():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32))
    eager = model(x).numpy()
    fast = paddle.jit.to_static(model)
    out = fast(x).numpy()
    np.testing.assert_allclose(out, eager, rtol=1e-5, atol=1e-6)
    out2 = fast(x).numpy()  # cached path
    np.testing.assert_allclose(out2, eager, rtol=1e-5, atol=1e-6)


def test_to_static_function_decorator():
    @paddle.jit.to_static
    def f(a, b):
        return paddle.matmul(a, b) + 1.0

    a = paddle.to_tensor(np.ones((2, 3), dtype=np.float32))
    b = paddle.to_tensor(np.ones((3, 2), dtype=np.float32))
    np.testing.assert_allclose(f(a, b).numpy(), np.full((2, 2), 4.0))


def test_trainstep_with_gradscaler_skip_and_rescale(rng):
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.api import TrainStep

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    optimizer = opt.SGD(learning_rate=1e-2, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(
        init_loss_scaling=2.0 ** 10, decr_every_n_nan_or_inf=1,
        incr_every_n_steps=3)
    mse = nn.MSELoss()
    step = TrainStep(model, lambda m, x, y: mse(m(x), y), optimizer,
                     scaler=scaler)
    x = paddle.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 1)).astype(np.float32))
    w0 = model[0].weight.numpy().copy()
    for _ in range(3):
        step(x, y)
    assert not np.allclose(model[0].weight.numpy(), w0)
    assert scaler.get_loss_scaling() == 2.0 ** 11
    w1 = model[0].weight.numpy().copy()
    xbad = paddle.to_tensor(np.full((8, 8), 1e38, np.float32))
    step(xbad, y)  # inf grads: update skipped, scale halves
    np.testing.assert_allclose(model[0].weight.numpy(), w1)
    assert scaler.get_loss_scaling() == 2.0 ** 10


def test_vision_zoo_extended_forward(rng):
    from paddle_tpu.vision import models as M

    x = paddle.to_tensor(rng.standard_normal((1, 3, 64, 64)).astype(np.float32))
    for ctor in (M.densenet121, M.squeezenet1_1, M.shufflenet_v2_x0_5,
                 M.googlenet):
        m = ctor(num_classes=4)
        m.eval()
        assert m(x).shape == [1, 4]
