"""Compiled-path NaN/Inf sanitizer (VERDICT r1 weak #8): with numerics
checking enabled, to_static programs and the jitted TrainStep surface
float errors via checkify (reference: FLAGS_check_nan_inf per instruction,
program_interpreter.cc:1131).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.amp import debugging
from paddle_tpu.jit import to_static
from paddle_tpu.jit.api import TrainStep


@pytest.fixture
def nan_check():
    debugging.enable_operator_stats_collection()
    yield
    debugging.disable_operator_stats_collection()


def test_to_static_flags_nan_inside_jit(nan_check):
    def fn(x):
        return paddle.log(x)  # log(-1) -> nan INSIDE the compiled program

    f = to_static(fn)
    with pytest.raises(Exception) as ei:
        out = f(paddle.to_tensor(np.asarray([-1.0], np.float32)))
        _ = out.numpy()
    assert "nan" in str(ei.value).lower()


def test_to_static_clean_program_passes(nan_check):
    f = to_static(lambda x: paddle.exp(x))
    out = f(paddle.to_tensor(np.asarray([1.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), np.e, rtol=1e-6)


def test_layer_bound_static_under_no_grad(nan_check):
    # review repro: checkify erases the signature, so `training` must be
    # static POSITIONALLY — layer-bound to_static under no_grad is the path
    paddle.framework.random.seed(0)
    model = to_static(nn.Linear(4, 2))
    with paddle.no_grad():
        out = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert np.all(np.isfinite(out.numpy()))
    with paddle.no_grad(), pytest.raises(Exception):
        bad = model(paddle.to_tensor(np.full((2, 4), np.inf, np.float32)))
        _ = bad.numpy()


def test_trainstep_flags_poisoned_batch(nan_check):
    paddle.framework.random.seed(0)
    model = nn.Linear(4, 2)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    lossfn = nn.MSELoss()

    def loss_fn(m, x, y):
        return lossfn(m(x), y)

    step = TrainStep(model, loss_fn, o)
    x = np.ones((2, 4), np.float32)
    y = np.ones((2, 2), np.float32)
    # clean step first (compiles both paths)
    loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert np.isfinite(float(loss.numpy()))
    x[0, 0] = np.inf
    with pytest.raises(Exception) as ei:
        loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
        _ = float(loss.numpy())
    msg = str(ei.value).lower()
    assert "nan" in msg or "inf" in msg or "div" in msg
