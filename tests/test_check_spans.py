"""tools/check_spans.py: the span-name manifest lint as a tier-1 test.

Every literal ``RecordEvent`` span under ``paddle_tpu/`` must be registered
in ``observability/span_manifest.py`` with an owner + category, stale
manifest entries must be removed, and runtime-built span names must be
declared per call-site file. Pure text scan — no jax import needed.
"""

import importlib.util
import os
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "check_spans", os.path.join(REPO, "tools", "check_spans.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_spans_all_registered():
    """The real lint: paddle_tpu/ against the live manifest."""
    cs = _load_tool()
    from paddle_tpu.observability.span_manifest import (
        DYNAMIC_SPANS,
        SPAN_MANIFEST,
    )

    report = cs.check_spans(os.path.join(REPO, "paddle_tpu"),
                            SPAN_MANIFEST, DYNAMIC_SPANS)
    assert report["ok"], {
        "unregistered": report["unregistered"],
        "stale": report["stale"],
        "undeclared_dynamic": report["undeclared_dynamic"],
        "malformed": report["malformed_entries"],
    }
    # the known serving spans are among the emitted set
    assert "serving.decode_step" in report["spans_emitted"]
    assert cs.main([]) == 0              # CLI face agrees


def test_lint_catches_unregistered_stale_and_dynamic(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent("""
        from paddle_tpu.profiler import RecordEvent

        def f(name):
            with RecordEvent("known.span"):
                pass
            with RecordEvent("rogue.span"):
                pass
            with RecordEvent(name):
                pass
            with RecordEvent(f"dyn.{name}"):
                pass
    """))
    cs = _load_tool()
    manifest = {
        "known.span": {"owner": "x", "category": "UserDefined"},
        "gone.span": {"owner": "x", "category": "UserDefined"},
        "bad.entry": {"owner": "", "category": "UserDefined"},
    }
    report = cs.check_spans(str(pkg), manifest, {})
    assert not report["ok"]
    assert "rogue.span" in report["unregistered"]
    assert "gone.span" in report["stale"]
    assert len(report["undeclared_dynamic"]) == 2   # variable + f-string
    assert "bad.entry" in report["malformed_entries"]
    # declaring the file fixes the dynamic violations
    report2 = cs.check_spans(
        str(pkg),
        {"known.span": {"owner": "x", "category": "UserDefined"},
         "rogue.span": {"owner": "x", "category": "UserDefined"}},
        {"pkg/mod.py": "dyn."})
    assert report2["undeclared_dynamic"] == []
    assert report2["ok"]
