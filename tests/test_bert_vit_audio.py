"""BERT, ViT, audio features, RPC (reference patterns: PaddleNLP bert tests,
PaddleClas vit tests, test/legacy_test/test_audio_functions.py, rpc tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_bert_classification_trains(rng):
    from paddle_tpu.models import BertForSequenceClassification, bert_tiny

    cfg = bert_tiny(num_layers=1)
    m = BertForSequenceClassification(cfg, num_classes=2)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (8, 12)).astype(np.int32))
    # plant the signal: class = whether token 0 is < vocab/2
    labels = paddle.to_tensor(
        (ids.numpy()[:, 0] < cfg.vocab_size // 2).astype(np.int64))
    ce = nn.CrossEntropyLoss()
    first = None
    for _ in range(25):
        loss = ce(m(ids), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    assert float(loss.numpy()) < first * 0.7


def test_bert_attention_mask_effect(rng):
    from paddle_tpu.models import BertModel, bert_tiny

    cfg = bert_tiny(num_layers=1)
    m = BertModel(cfg)
    m.eval()
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (1, 8)).astype(np.int32))
    full = paddle.to_tensor(np.ones((1, 8), np.int32))
    half = paddle.to_tensor(
        np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32))
    h_full, _ = m(ids, attention_mask=full)
    h_half, _ = m(ids, attention_mask=half)
    # masking the tail must change the first token's representation
    assert np.abs(h_full.numpy()[0, 0] - h_half.numpy()[0, 0]).max() > 1e-5


def test_bert_pretraining_heads(rng):
    from paddle_tpu.models import BertForPretraining, bert_tiny

    cfg = bert_tiny(num_layers=1)
    m = BertForPretraining(cfg)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32))
    mlm, nsp = m(ids)
    assert mlm.shape == [2, 8, cfg.vocab_size]
    assert nsp.shape == [2, 2]


def test_vit_forward_and_patch_count(rng):
    from paddle_tpu.models import VisionTransformer, vit_tiny

    cfg = vit_tiny()
    m = VisionTransformer(cfg)
    m.eval()
    x = paddle.to_tensor(rng.standard_normal((2, 3, 32, 32)).astype(np.float32))
    out = m(x)
    assert out.shape == [2, 10]
    assert cfg.num_patches == 16  # (32/8)^2


def test_vit_base_param_count():
    from paddle_tpu.models import VisionTransformer, vit_base_patch16_224

    m = VisionTransformer(vit_base_patch16_224())
    n = sum(int(np.prod(p.shape)) for p in m.parameters())
    # ViT-B/16: ~86.6M params
    assert abs(n - 86_567_656) < 200_000, n


def test_spectrogram_peak_bin():
    from paddle_tpu.audio.features import Spectrogram

    sr, f = 8000, 1000.0
    t = np.arange(8000) / sr
    x = paddle.to_tensor(np.sin(2 * np.pi * f * t).astype(np.float32)[None])
    spec = Spectrogram(n_fft=256, hop_length=128)(x).numpy()[0]
    peak_bin = spec.mean(axis=1).argmax()
    expected = round(f * 256 / sr)
    assert abs(int(peak_bin) - expected) <= 1


def test_melspectrogram_shapes_and_mono():
    from paddle_tpu.audio.features import LogMelSpectrogram, MelSpectrogram

    x = paddle.to_tensor(np.random.randn(2, 4000).astype(np.float32))
    mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=40)(x)
    assert mel.shape[0] == 2 and mel.shape[1] == 40
    logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=40)(x)
    assert logmel.shape == mel.shape


def test_mfcc_dct_orthonormal():
    from paddle_tpu.audio.functional import create_dct

    d = create_dct(13, 40).numpy()  # [40, 13]
    gram = d.T @ d
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)


def test_rpc_sync_async_and_exceptions():
    import operator

    import paddle_tpu.distributed.rpc as rpc
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    rpc.init_rpc("w0", rank=0, world_size=1, store=store)
    try:
        assert rpc.rpc_sync("w0", operator.add, args=(2, 3)) == 5
        fut = rpc.rpc_async("w0", operator.mul, args=(6, 7))
        assert fut.wait(30) == 42
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("w0", operator.truediv, args=(1, 0))
        info = rpc.get_worker_info()
        assert info.name == "w0" and info.rank == 0
    finally:
        rpc.shutdown()
