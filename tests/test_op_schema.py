"""Schema-driven audits over the rich op manifest (VERDICT r1 missing #7:
OpSpec had no backward/inplace/optional metadata and no schema audits).

REFERENCE_SCHEMA carries per-op arity, backward op, inplace aliases,
optional args, and view outputs parsed from the reference YAML; these
audits enforce consistency between that schema and the live registry.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.parity import SKIPPED_OPS
from paddle_tpu.ops.ref_manifest import REFERENCE_OPS, REFERENCE_SCHEMA
from paddle_tpu.ops.registry import all_ops

# ops where the reference HAS a backward but this registry marks the op
# non-differentiable — each carries a reason (the reverse direction, ops WE
# differentiate beyond the reference, is a capability superset by design:
# jax.vjp derives gradients the reference never hand-wrote)
NON_DIFF_EXCEPTIONS = {
    "argsort": "returns indices; values-path grad is a permutation gather, covered by sort",
    "eig": "complex eigendecomposition vjp unsupported on this substrate",
    "lu": "pivoted-LU vjp not provided by jax; lu_unpack covers use",
    "mode": "returns (values, indices); indices dominate usage",
    "poisson": "sampling op; reference's grad is a zero-pass-through",
    "exponential_": "sampling op; reference's grad is zero",
    "uniform_inplace": "sampling op",
    "gaussian_inplace": "sampling op",
    "disable_check_model_nan_inf": "debug toggle; backward key is an artifact",
    "enable_check_model_nan_inf": "debug toggle; backward key is an artifact",
}


def test_schema_fields_populated():
    assert len(REFERENCE_SCHEMA) == len(REFERENCE_OPS) == 538
    with_bwd = [n for n, m in REFERENCE_SCHEMA.items() if m["backward"]]
    with_inplace = [n for n, m in REFERENCE_SCHEMA.items() if m["inplace"]]
    assert len(with_bwd) > 250
    assert len(with_inplace) > 80
    for n, m in REFERENCE_SCHEMA.items():
        assert m["n_inputs"] >= 0 and m["n_outputs"] >= 1, n


def test_differentiability_matches_backward_schema():
    reg = all_ops()
    missing_grad = []
    for n, meta in REFERENCE_SCHEMA.items():
        if n in SKIPPED_OPS or n not in reg:
            continue
        if (meta["backward"] and not reg[n].differentiable
                and n not in NON_DIFF_EXCEPTIONS):
            missing_grad.append(n)
    assert not missing_grad, (
        f"reference defines a backward but the registered op is "
        f"non-differentiable (add the gradient or a justified exception): "
        f"{missing_grad}")


def test_inplace_variants_registered():
    reg = all_ops()
    want = [n for n, m in REFERENCE_SCHEMA.items()
            if m["inplace"] and not n.endswith("_")
            and n in reg and n not in SKIPPED_OPS]
    have = [n for n in want if (n + "_") in reg]
    cov = len(have) / len(want)
    assert cov >= 0.9, (
        f"inplace-alias coverage {cov:.0%}; missing: "
        f"{sorted(set(want) - set(have))[:20]}")


def test_inplace_semantics_mutate_first_arg():
    x = paddle.to_tensor(np.asarray([-1.0, 2.0, -3.0], np.float32))
    reg = all_ops()
    relu_ = reg["relu_"].fn
    out = relu_(x)
    assert out is x
    np.testing.assert_allclose(x.numpy(), [0.0, 2.0, 0.0])

    y = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    reg["scale_"].fn(y, scale=2.0)
    np.testing.assert_allclose(y.numpy(), [2.0, 4.0])


def test_inplace_on_grad_tensor_raises():
    # reference semantics: in-place on a tensor that requires grad errors
    # instead of silently dropping the gradient
    reg = all_ops()
    x = paddle.to_tensor(np.asarray([-1.0, 2.0], np.float32),
                         stop_gradient=False)
    with pytest.raises(RuntimeError, match="in-place"):
        reg["relu_"].fn(x)


def test_where_inplace_mutates_x_not_condition():
    reg = all_ops()
    cond = paddle.to_tensor(np.asarray([True, False]))
    a = paddle.to_tensor(np.asarray([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.asarray([8.0, 9.0], np.float32))
    out = reg["where_"].fn(cond, a, b)
    assert out is a
    np.testing.assert_allclose(a.numpy(), [1.0, 9.0])
    assert cond.numpy().dtype == np.bool_  # condition untouched


def test_optional_and_view_metadata_accessible():
    # spot checks that the schema round-tripped the YAML keys
    assert REFERENCE_SCHEMA["dropout"]["optional"] == "seed_tensor"
    assert REFERENCE_SCHEMA["dropout"]["backward"] == "dropout_grad"
    assert "param -> param_out" in REFERENCE_SCHEMA["adam_"]["inplace"]
    views = [n for n, m in REFERENCE_SCHEMA.items() if m["view"]]
    assert views  # reshape/squeeze family
