"""Round-5 distribution completion: transform machinery +
TransformedDistribution/Independent + the 11 added distributions, pinned
against torch.distributions (CPU) closed forms where available and against
analytic identities otherwise (reference: python/paddle/distribution/
transform.py, independent.py, transformed_distribution.py,
multivariate_normal.py, student_t.py, poisson.py, geometric.py, cauchy.py,
chi2.py, binomial.py, continuous_bernoulli.py, lkj_cholesky.py)."""

import math

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


def n(x):
    return np.asarray(x.numpy())


# --------------------------------------------------------------- transforms
class TestTransforms:
    def test_affine_roundtrip_and_ldj(self):
        tr = D.AffineTransform(t(2.0), t(3.0))
        x = t(np.linspace(-2, 2, 7))
        y = tr.forward(x)
        np.testing.assert_allclose(n(y), 2.0 + 3.0 * n(x), rtol=1e-6)
        np.testing.assert_allclose(n(tr.inverse(y)), n(x), rtol=1e-5)
        np.testing.assert_allclose(n(tr.forward_log_det_jacobian(x)),
                                   np.full(7, math.log(3.0)), rtol=1e-6)
        assert tr.forward_shape((7,)) == (7,)

    def test_exp_tanh_sigmoid_ldj_vs_torch(self):
        x_np = np.linspace(-2.5, 2.5, 11).astype(np.float32)
        pairs = [
            (D.ExpTransform(), torch.distributions.ExpTransform()),
            (D.TanhTransform(), torch.distributions.TanhTransform()),
            (D.SigmoidTransform(), torch.distributions.SigmoidTransform()),
        ]
        for ours, theirs in pairs:
            y = ours.forward(t(x_np))
            yt = theirs(torch.tensor(x_np))
            np.testing.assert_allclose(n(y), yt.numpy(), rtol=1e-5,
                                       atol=1e-6)
            ldj = ours.forward_log_det_jacobian(t(x_np))
            ldj_t = theirs.log_abs_det_jacobian(torch.tensor(x_np), yt)
            np.testing.assert_allclose(n(ldj), ldj_t.numpy(), rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(n(ours.inverse(y)), x_np, rtol=1e-4,
                                       atol=1e-5)

    def test_power_transform(self):
        tr = D.PowerTransform(t(2.0))
        x = t([1.0, 2.0, 3.0])
        np.testing.assert_allclose(n(tr.forward(x)), [1, 4, 9], rtol=1e-6)
        np.testing.assert_allclose(n(tr.inverse(tr.forward(x))), n(x),
                                   rtol=1e-6)
        # d(x^2)/dx = 2x
        np.testing.assert_allclose(n(tr.forward_log_det_jacobian(x)),
                                   np.log(2 * np.array([1., 2., 3.])),
                                   rtol=1e-6)

    def test_abs_transform_set_inverse(self):
        tr = D.AbsTransform()
        assert not tr._is_injective()
        lo, hi = tr.inverse(t([1.0, 2.0]))
        np.testing.assert_allclose(n(lo), [-1, -2])
        np.testing.assert_allclose(n(hi), [1, 2])

    def test_chain_matches_torch_compose(self):
        x_np = np.linspace(-1.5, 1.5, 9).astype(np.float32)
        ours = D.ChainTransform(
            [D.AffineTransform(t(0.5), t(2.0)), D.TanhTransform()])
        theirs = torch.distributions.ComposeTransform([
            torch.distributions.AffineTransform(0.5, 2.0),
            torch.distributions.TanhTransform()])
        y = ours.forward(t(x_np))
        yt = theirs(torch.tensor(x_np))
        np.testing.assert_allclose(n(y), yt.numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            n(ours.forward_log_det_jacobian(t(x_np))),
            theirs.log_abs_det_jacobian(torch.tensor(x_np), yt).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_stickbreaking_vs_torch(self):
        x_np = np.array([0.3, -0.7, 1.1], np.float32)
        ours = D.StickBreakingTransform()
        theirs = torch.distributions.StickBreakingTransform()
        y = ours.forward(t(x_np))
        yt = theirs(torch.tensor(x_np))
        np.testing.assert_allclose(n(y), yt.numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(n(ours.inverse(y)), x_np, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(
            n(ours.forward_log_det_jacobian(t(x_np))),
            theirs.log_abs_det_jacobian(torch.tensor(x_np), yt).numpy(),
            rtol=1e-4, atol=1e-5)
        assert ours.forward_shape((3,)) == (4,)
        assert ours.inverse_shape((4,)) == (3,)

    def test_softmax_and_reshape_and_stack(self):
        x = t(np.arange(6, dtype=np.float32).reshape(2, 3))
        sm = D.SoftmaxTransform()
        y = sm.forward(x)
        np.testing.assert_allclose(n(y).sum(-1), [1, 1], rtol=1e-6)
        rs = D.ReshapeTransform((2, 3), (3, 2))
        np.testing.assert_allclose(n(rs.forward(x)),
                                   n(x).reshape(3, 2))
        assert rs.forward_shape((5, 2, 3)) == (5, 3, 2)
        st = D.StackTransform([D.ExpTransform(), D.AffineTransform(
            t(0.0), t(2.0))], axis=0)
        xs = t(np.stack([np.zeros(3, np.float32),
                         np.ones(3, np.float32)]))
        out = n(st.forward(xs))
        np.testing.assert_allclose(out[0], np.ones(3), rtol=1e-6)
        np.testing.assert_allclose(out[1], 2 * np.ones(3), rtol=1e-6)

    def test_independent_transform_sums_ldj(self):
        base = D.ExpTransform()
        it = D.IndependentTransform(base, 1)
        x = t(np.ones((2, 3), np.float32))
        ldj = n(it.forward_log_det_jacobian(x))
        assert ldj.shape == (2,)
        np.testing.assert_allclose(ldj, [3.0, 3.0], rtol=1e-6)

    def test_call_dispatch(self):
        tr = D.ExpTransform()
        # Transform(Distribution) -> TransformedDistribution
        td = tr(D.Normal(t(0.0), t(1.0)))
        assert isinstance(td, D.TransformedDistribution)
        # Transform(Transform) -> ChainTransform
        ch = tr(D.TanhTransform())
        assert isinstance(ch, D.ChainTransform)
        # Transform(Tensor) -> Tensor
        out = tr(t([0.0]))
        np.testing.assert_allclose(n(out), [1.0], rtol=1e-6)


# ------------------------------------------------- wrappers over base dists
class TestWrappers:
    def test_independent_log_prob_entropy(self):
        loc = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        ours = D.Independent(D.Normal(t(loc), t(np.ones((3, 4)))), 1)
        theirs = torch.distributions.Independent(
            torch.distributions.Normal(torch.tensor(loc), 1.0), 1)
        v = np.zeros((3, 4), np.float32)
        np.testing.assert_allclose(n(ours.log_prob(t(v))),
                                   theirs.log_prob(torch.tensor(v)).numpy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(n(ours.entropy()),
                                   theirs.entropy().numpy(), rtol=1e-5)
        assert ours.batch_shape == (3,) and ours.event_shape == (4,)

    def test_transformed_distribution_log_prob(self):
        # exp(Normal) == LogNormal
        ours = D.TransformedDistribution(D.Normal(t(0.3), t(0.8)),
                                         [D.ExpTransform()])
        theirs = torch.distributions.TransformedDistribution(
            torch.distributions.Normal(0.3, 0.8),
            [torch.distributions.ExpTransform()])
        for v in (0.5, 1.0, 2.5):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-5)
        s = n(ours.sample((2000,)))
        assert (s > 0).all()

    def test_transformed_distribution_chain_tanh_affine(self):
        trs = [D.AffineTransform(t(0.0), t(0.5)), D.TanhTransform()]
        ours = D.TransformedDistribution(D.Normal(t(0.0), t(1.0)), trs)
        theirs = torch.distributions.TransformedDistribution(
            torch.distributions.Normal(0.0, 1.0),
            [torch.distributions.AffineTransform(0.0, 0.5),
             torch.distributions.TanhTransform()])
        for v in (-0.5, 0.1, 0.7):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-4)


# ------------------------------------------------------ added distributions
class TestAddedDistributions:
    def test_multivariate_normal_vs_torch(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=(3, 3)).astype(np.float32)
        cov = (a @ a.T + 3 * np.eye(3)).astype(np.float32)
        loc = rng.normal(size=3).astype(np.float32)
        ours = D.MultivariateNormal(t(loc), covariance_matrix=t(cov))
        theirs = torch.distributions.MultivariateNormal(
            torch.tensor(loc), covariance_matrix=torch.tensor(cov))
        v = rng.normal(size=3).astype(np.float32)
        np.testing.assert_allclose(float(n(ours.log_prob(t(v)))),
                                   float(theirs.log_prob(torch.tensor(v))),
                                   rtol=1e-4)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-5)
        s = n(ours.sample((20000,)))
        np.testing.assert_allclose(s.mean(0), loc, atol=0.1)
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.35)

    def test_multivariate_normal_parameterizations_agree(self):
        rng = np.random.default_rng(2)
        a = rng.normal(size=(2, 2)).astype(np.float32)
        cov = (a @ a.T + 2 * np.eye(2)).astype(np.float32)
        prec = np.linalg.inv(cov).astype(np.float32)
        tril = np.linalg.cholesky(cov).astype(np.float32)
        loc = t([0.5, -1.0])
        v = t([0.2, 0.3])
        lps = [float(n(D.MultivariateNormal(
            loc, covariance_matrix=t(cov)).log_prob(v))),
            float(n(D.MultivariateNormal(
                loc, precision_matrix=t(prec)).log_prob(v))),
            float(n(D.MultivariateNormal(
                loc, scale_tril=t(tril)).log_prob(v)))]
        np.testing.assert_allclose(lps[0], lps[1], rtol=1e-4)
        np.testing.assert_allclose(lps[0], lps[2], rtol=1e-5)
        with pytest.raises(ValueError):
            D.MultivariateNormal(loc, covariance_matrix=t(cov),
                                 scale_tril=t(tril))

    def test_mvn_kl_vs_torch(self):
        rng = np.random.default_rng(3)
        a = rng.normal(size=(2, 2)).astype(np.float32)
        b = rng.normal(size=(2, 2)).astype(np.float32)
        c1 = (a @ a.T + 2 * np.eye(2)).astype(np.float32)
        c2 = (b @ b.T + 2 * np.eye(2)).astype(np.float32)
        p = D.MultivariateNormal(t([0., 0.]), covariance_matrix=t(c1))
        q = D.MultivariateNormal(t([1., -1.]), covariance_matrix=t(c2))
        pt = torch.distributions.MultivariateNormal(
            torch.zeros(2), covariance_matrix=torch.tensor(c1))
        qt = torch.distributions.MultivariateNormal(
            torch.tensor([1., -1.]), covariance_matrix=torch.tensor(c2))
        np.testing.assert_allclose(
            float(n(D.kl_divergence(p, q)).reshape(())),
            float(torch.distributions.kl_divergence(pt, qt)), rtol=1e-4)

    def test_student_t_vs_torch(self):
        ours = D.StudentT(t(5.0), t(0.5), t(2.0))
        theirs = torch.distributions.StudentT(5.0, 0.5, 2.0)
        for v in (-1.0, 0.5, 3.0):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.mean).reshape(())), 0.5)
        s = n(ours.sample((30000,)))
        np.testing.assert_allclose(s.mean(), 0.5, atol=0.1)

    def test_poisson_vs_torch(self):
        ours = D.Poisson(t([3.0, 10.0]))
        theirs = torch.distributions.Poisson(torch.tensor([3.0, 10.0]))
        v = np.array([2.0, 11.0], np.float32)
        np.testing.assert_allclose(n(ours.log_prob(t(v))),
                                   theirs.log_prob(torch.tensor(v)).numpy(),
                                   rtol=1e-5)
        # enumeration entropy vs scipy-style exact: torch has no
        # .entropy for Poisson; check against direct summation
        lam = 3.0
        ks = np.arange(200)
        from scipy.stats import poisson as sp  # noqa: F401

        logp = ks * math.log(lam) - lam - \
            np.array([math.lgamma(k + 1) for k in ks])
        h = -(np.exp(logp) * logp).sum()
        np.testing.assert_allclose(float(n(ours.entropy())[0]), h, rtol=1e-4)
        s = n(ours.sample((20000,)))
        np.testing.assert_allclose(s.mean(0), [3.0, 10.0], rtol=0.05)

    def test_geometric_vs_torch(self):
        ours = D.Geometric(t(0.3))
        theirs = torch.distributions.Geometric(torch.tensor(0.3))
        for k in (0.0, 1.0, 5.0):
            np.testing.assert_allclose(
                float(n(ours.log_pmf(t(k)))),
                float(theirs.log_prob(torch.tensor(k))), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.mean).reshape(())),
                                   float(theirs.mean), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-4)
        # cdf identity: P(X <= k) = 1 - (1-p)^(k+1)
        np.testing.assert_allclose(float(n(ours.cdf(t(2.0)))),
                                   1 - 0.7 ** 3, rtol=1e-5)
        s = n(ours.sample((20000,)))
        assert (s >= 0).all()
        np.testing.assert_allclose(s.mean(), 0.7 / 0.3, rtol=0.08)

    def test_cauchy_vs_torch(self):
        ours = D.Cauchy(t(0.5), t(2.0))
        theirs = torch.distributions.Cauchy(0.5, 2.0)
        for v in (-2.0, 0.5, 4.0):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-5)
            np.testing.assert_allclose(
                float(n(ours.cdf(t(v)))),
                float(theirs.cdf(torch.tensor(v))), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-5)
        with pytest.raises(ValueError):
            _ = ours.mean
        p2, q2 = D.Cauchy(t(0.0), t(1.0)), D.Cauchy(t(1.0), t(2.0))
        pt, qt = torch.distributions.Cauchy(0.0, 1.0), \
            torch.distributions.Cauchy(1.0, 2.0)
        np.testing.assert_allclose(
            float(n(D.kl_divergence(p2, q2)).reshape(())),
            float(torch.distributions.kl_divergence(pt, qt)), rtol=1e-4)

    def test_chi2_vs_torch(self):
        ours = D.Chi2(t(4.0))
        theirs = torch.distributions.Chi2(4.0)
        for v in (1.0, 3.0, 8.0):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.df).reshape(())), 4.0)

    def test_binomial_vs_torch(self):
        ours = D.Binomial(10, t(0.4))
        theirs = torch.distributions.Binomial(10, torch.tensor(0.4))
        for k in (0.0, 3.0, 10.0):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(k)))),
                float(theirs.log_prob(torch.tensor(k))), rtol=1e-5)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-4)
        np.testing.assert_allclose(float(n(ours.mean).reshape(())), 4.0)
        s = n(ours.sample((20000,)))
        assert ((s >= 0) & (s <= 10)).all()
        np.testing.assert_allclose(s.mean(), 4.0, rtol=0.05)
        pk, qk = D.Binomial(10, t(0.4)), D.Binomial(10, t(0.6))
        pt, qt = torch.distributions.Binomial(10, torch.tensor(0.4)), \
            torch.distributions.Binomial(10, torch.tensor(0.6))
        np.testing.assert_allclose(
            float(n(D.kl_divergence(pk, qk)).reshape(())),
            float(torch.distributions.kl_divergence(pt, qt)), rtol=1e-4)

    def test_continuous_bernoulli_vs_torch(self):
        ours = D.ContinuousBernoulli(t(0.3))
        theirs = torch.distributions.ContinuousBernoulli(torch.tensor(0.3))
        for v in (0.1, 0.5, 0.9):
            np.testing.assert_allclose(
                float(n(ours.log_prob(t(v)))),
                float(theirs.log_prob(torch.tensor(v))), rtol=1e-4)
        np.testing.assert_allclose(float(n(ours.mean).reshape(())),
                                   float(theirs.mean), rtol=1e-4)
        np.testing.assert_allclose(float(n(ours.variance).reshape(())),
                                   float(theirs.variance), rtol=1e-4)
        np.testing.assert_allclose(float(n(ours.entropy()).reshape(())),
                                   float(theirs.entropy()), rtol=1e-4)
        # Taylor branch near 0.5 stays finite and close to exact-at-0.502
        near = D.ContinuousBernoulli(t(0.5))
        assert np.isfinite(float(n(near.log_prob(t(0.4)))))
        np.testing.assert_allclose(float(n(near.mean).reshape(())), 0.5,
                                   atol=1e-5)
        s = n(ours.sample((20000,)))
        assert ((s >= 0) & (s <= 1)).all()
        np.testing.assert_allclose(s.mean(), float(theirs.mean), atol=0.01)

    def test_lkj_cholesky_vs_torch(self):
        ours = D.LKJCholesky(4, 2.0)
        theirs = torch.distributions.LKJCholesky(4, 2.0)
        ls = n(ours.sample((500,)))
        # valid cholesky factors of correlation matrices
        for L in ls[:10]:
            assert np.allclose(np.triu(L, 1), 0)
            corr = L @ L.T
            np.testing.assert_allclose(np.diag(corr), np.ones(4), atol=1e-5)
        # log_prob parity with torch on torch's own samples
        lt = theirs.sample((8,))
        np.testing.assert_allclose(
            n(ours.log_prob(t(lt.numpy()))),
            theirs.log_prob(lt).numpy(), rtol=1e-4)
        # cvine sampler also produces valid factors
        cv = D.LKJCholesky(3, 1.0, sample_method="cvine")
        lc = n(cv.sample((100,)))
        for L in lc[:5]:
            np.testing.assert_allclose(np.diag(L @ L.T), np.ones(3),
                                       atol=1e-5)
        with pytest.raises(ValueError):
            D.LKJCholesky(1, 1.0)
        with pytest.raises(ValueError):
            D.LKJCholesky(3, 1.0, sample_method="bogus")

    def test_gamma_exponential_entropy_kl(self):
        g = D.Gamma(t(3.0), t(2.0))
        gt = torch.distributions.Gamma(3.0, 2.0)
        np.testing.assert_allclose(float(n(g.entropy()).reshape(())),
                                   float(gt.entropy()), rtol=1e-5)
        np.testing.assert_allclose(float(n(g.mean).reshape(())), 1.5)
        g2 = D.Gamma(t(2.0), t(1.0))
        gt2 = torch.distributions.Gamma(2.0, 1.0)
        np.testing.assert_allclose(
            float(n(D.kl_divergence(g, g2)).reshape(())),
            float(torch.distributions.kl_divergence(gt, gt2)), rtol=1e-4)
        e1, e2 = D.Exponential(t(2.0)), D.Exponential(t(0.5))
        et1, et2 = torch.distributions.Exponential(2.0), \
            torch.distributions.Exponential(0.5)
        np.testing.assert_allclose(
            float(n(D.kl_divergence(e1, e2)).reshape(())),
            float(torch.distributions.kl_divergence(et1, et2)), rtol=1e-4)

    def test_geometric_kl(self):
        p, q = D.Geometric(t(0.3)), D.Geometric(t(0.6))
        pt, qt = torch.distributions.Geometric(torch.tensor(0.3)), \
            torch.distributions.Geometric(torch.tensor(0.6))
        np.testing.assert_allclose(
            float(n(D.kl_divergence(p, q)).reshape(())),
            float(torch.distributions.kl_divergence(pt, qt)), rtol=1e-4)


class TestNamespaceParity:
    def test_all_matches_reference(self):
        """Every name the reference's distribution __all__ exports exists
        here (reference python/paddle/distribution/__init__.py:72)."""
        import ast
        import pathlib

        ref = pathlib.Path(
            "/root/reference/python/paddle/distribution/__init__.py")
        if not ref.exists():
            pytest.skip("reference tree unavailable")
        tree = ast.parse(ref.read_text())
        names = []
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    getattr(x, "id", "") == "__all__"
                    for x in node.targets):
                names = [ast.literal_eval(e) for e in node.value.elts]
        assert names, "no __all__ found in reference"
        missing = [nm for nm in names if not hasattr(D, nm)]
        assert not missing, f"missing distribution names: {missing}"


class TestExponentialFamily:
    def test_bregman_entropy_matches_closed_forms(self):
        """ExponentialFamily.entropy (H = F(θ) - <θ, ∇F(θ)> - E[log h])
        must reproduce the closed-form entropies when a distribution is
        expressed in natural parameters (reference exponential_family.py
        uses the same autodiff identity)."""
        import jax.numpy as jnp

        class NormalEF(D.ExponentialFamily):
            # N(mu, sigma^2): theta = (mu/s^2, -1/(2 s^2)),
            # F = -t1^2/(4 t2) - log(-2 t2)/2, log h = -log(2pi)/2
            def __init__(self, loc, scale):
                self.loc, self.scale = float(loc), float(scale)
                super().__init__(())

            @property
            def _natural_parameters(self):
                s2 = self.scale ** 2
                return (self.loc / s2, -0.5 / s2)

            def _log_normalizer(self, t1, t2):
                return -(t1 ** 2) / (4 * t2) - 0.5 * jnp.log(-2.0 * t2)

            @property
            def _mean_carrier_measure(self):
                return -0.5 * np.log(2 * np.pi)

        for loc, scale in ((0.0, 1.0), (2.0, 0.5), (-1.0, 3.0)):
            h = float(n(NormalEF(loc, scale).entropy()).reshape(()))
            expected = float(torch.distributions.Normal(loc, scale)
                             .entropy())
            np.testing.assert_allclose(h, expected, rtol=1e-5)

        class BernoulliEF(D.ExponentialFamily):
            # theta = logit(p), F = log(1 + e^theta), log h = 0
            def __init__(self, p):
                self.p = float(p)
                super().__init__(())

            @property
            def _natural_parameters(self):
                return (np.log(self.p) - np.log1p(-self.p),)

            def _log_normalizer(self, t):
                return jnp.log1p(jnp.exp(t))

            @property
            def _mean_carrier_measure(self):
                return 0.0

        for p in (0.2, 0.5, 0.9):
            h = float(n(BernoulliEF(p).entropy()).reshape(()))
            expected = float(torch.distributions.Bernoulli(
                torch.tensor(p)).entropy())
            np.testing.assert_allclose(h, expected, rtol=1e-5)

    def test_generic_expfamily_kl_matches_closed_form(self):
        """The Bregman-divergence generic KL (reference kl.py
        _kl_expfamily_expfamily) vs the Normal closed form."""
        import jax.numpy as jnp

        class NormalEF(D.ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc, self.scale = float(loc), float(scale)
                super().__init__(())

            @property
            def _natural_parameters(self):
                s2 = self.scale ** 2
                return (self.loc / s2, -0.5 / s2)

            def _log_normalizer(self, t1, t2):
                return -(t1 ** 2) / (4 * t2) - 0.5 * jnp.log(-2.0 * t2)

            @property
            def _mean_carrier_measure(self):
                return -0.5 * np.log(2 * np.pi)

        p, q = NormalEF(0.5, 1.5), NormalEF(-1.0, 0.7)
        kl = float(n(D.kl_divergence(p, q)).reshape(()))
        expected = float(torch.distributions.kl_divergence(
            torch.distributions.Normal(0.5, 1.5),
            torch.distributions.Normal(-1.0, 0.7)))
        np.testing.assert_allclose(kl, expected, rtol=1e-4)


class TestMoreKLs:
    def test_laplace_lognormal_dirichlet_kls_vs_torch(self):
        pairs = [
            (D.Laplace(t(0.0), t(1.0)), D.Laplace(t(1.0), t(2.0)),
             torch.distributions.Laplace(0.0, 1.0),
             torch.distributions.Laplace(1.0, 2.0)),
            (D.LogNormal(t(0.2), t(0.8)), D.LogNormal(t(-0.3), t(1.1)),
             torch.distributions.LogNormal(0.2, 0.8),
             torch.distributions.LogNormal(-0.3, 1.1)),
            (D.Dirichlet(t([1.0, 2.0, 3.0])), D.Dirichlet(t([2.0, 2.0, 2.0])),
             torch.distributions.Dirichlet(torch.tensor([1.0, 2.0, 3.0])),
             torch.distributions.Dirichlet(torch.tensor([2.0, 2.0, 2.0]))),
        ]
        for p, q, pt, qt in pairs:
            np.testing.assert_allclose(
                float(n(D.kl_divergence(p, q)).reshape(())),
                float(torch.distributions.kl_divergence(pt, qt)),
                rtol=1e-4, err_msg=type(p).__name__)

    def test_generic_expfamily_kl_vector_event(self):
        """Vector-event EF (diagonal normal, event_shape (d,)): the
        generic KL must sum the inner product over event dims (r5
        review-caught bug: unsummed terms gave wrong shape AND value)."""
        import jax.numpy as jnp

        locs_p, scale_p = np.array([0.5, -1.0], np.float32), 1.5
        locs_q, scale_q = np.array([-1.0, 2.0], np.float32), 0.7

        class DiagNormalEF(D.ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc, self.scale = np.asarray(loc), float(scale)
                super().__init__((), (len(self.loc),))

            @property
            def _natural_parameters(self):
                s2 = self.scale ** 2
                return (jnp.asarray(self.loc / s2),
                        jnp.full(self.loc.shape, -0.5 / s2))

            def _log_normalizer(self, t1, t2):
                return jnp.sum(-(t1 ** 2) / (4 * t2)
                               - 0.5 * jnp.log(-2.0 * t2))

            @property
            def _mean_carrier_measure(self):
                return -0.5 * len(self.loc) * np.log(2 * np.pi)

        kl = n(D.kl_divergence(DiagNormalEF(locs_p, scale_p),
                               DiagNormalEF(locs_q, scale_q)))
        assert kl.shape == () or kl.size == 1
        expected = float(torch.distributions.kl_divergence(
            torch.distributions.Independent(
                torch.distributions.Normal(torch.tensor(locs_p), scale_p), 1),
            torch.distributions.Independent(
                torch.distributions.Normal(torch.tensor(locs_q), scale_q),
                1)))
        np.testing.assert_allclose(float(kl.reshape(())), expected,
                                   rtol=1e-4)

    def test_specific_kl_beats_expfamily_catchall(self):
        """A user's (MyEF, MyEF) registration must win over the earlier
        (ExponentialFamily, ExponentialFamily) catch-all (r5 review:
        first-match dispatch shadowed user registrations)."""

        class MyEF(D.ExponentialFamily):
            def __init__(self):
                super().__init__(())

        @D.register_kl(MyEF, MyEF)
        def _kl_my(p, q):
            return paddle.to_tensor(np.float32(42.0))

        out = float(n(D.kl_divergence(MyEF(), MyEF())))
        assert out == 42.0
