"""dy2static AST transformer: data-dependent if/while captured into the
compiled program (reference: jit/dy2static/transformers/, tests
test/dygraph_to_static/test_ifelse.py, test_while_op.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import UNDEF, ast_transform, convert_ifelse


def test_plain_python_semantics_preserved():
    def f(x, flag):
        if flag > 2:  # python int predicate: stays python
            y = x * 2
        else:
            y = x - 1
        return y

    g = ast_transform(f)
    x = paddle.to_tensor(np.float32(3.0))
    np.testing.assert_allclose(g(x, 5).numpy(), 6.0)
    np.testing.assert_allclose(g(x, 0).numpy(), 2.0)


def test_tensor_if_executes_data_dependently():
    def f(x):
        if (x.sum() > 0):
            y = x * 2
        else:
            y = -x
        return y

    g = ast_transform(f)
    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(g(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(g(neg).numpy(), [1.0, 2.0])


def test_tensor_if_inside_jit_single_program():
    import jax

    def f(x):
        if (x.sum() > 0):
            y = x * 2
        else:
            y = -x
        return y

    sf = paddle.jit.to_static(f)
    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    # same compiled program serves BOTH branches: data-dependent lax.cond
    np.testing.assert_allclose(np.asarray(sf(pos).numpy()), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray(sf(neg).numpy()), [1.0, 2.0])


def test_tensor_while_loop():
    def f(x):
        s = x * 0
        while (s.sum() < 10):
            s = s + x
        return s

    g = ast_transform(f)
    x = paddle.to_tensor(np.array([3.0], np.float32))
    np.testing.assert_allclose(g(x).numpy(), [12.0])


def test_python_while_untouched():
    def f(x, n):
        i = 0
        while i < n:  # python loop: unrolled at trace time
            x = x + 1
            i = i + 1
        return x

    g = ast_transform(f)
    x = paddle.to_tensor(np.float32(0.0))
    np.testing.assert_allclose(g(x, 3).numpy(), 3.0)


def test_branch_gradients_flow():
    def f(x):
        if (x.sum() > 0):
            y = x * x
        else:
            y = x * 3
        return y.sum()

    g = ast_transform(f)
    x = paddle.to_tensor(np.array([2.0, 1.0], np.float32),
                         stop_gradient=False)
    loss = g(x)
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 2.0])


def test_var_defined_in_branch_only():
    def f(x):
        if (x.sum() > 0):
            z = x * 2
        else:
            z = x * 5
        return z

    g = ast_transform(f)
    x = paddle.to_tensor(np.array([1.0], np.float32))
    np.testing.assert_allclose(g(x).numpy(), [2.0])


def test_nested_if():
    def f(x):
        if (x.sum() > 0):
            if (x.sum() > 10):
                y = x * 100
            else:
                y = x * 2
        else:
            y = -x
        return y

    g = ast_transform(f)
    np.testing.assert_allclose(
        g(paddle.to_tensor(np.array([20.0], np.float32))).numpy(), [2000.0])
    np.testing.assert_allclose(
        g(paddle.to_tensor(np.array([1.0], np.float32))).numpy(), [2.0])
    np.testing.assert_allclose(
        g(paddle.to_tensor(np.array([-1.0], np.float32))).numpy(), [1.0])


def test_return_inside_branch_left_alone():
    def f(x, flag):
        if flag:
            return x * 2
        return x

    g = ast_transform(f)  # escape => untransformed, python semantics
    x = paddle.to_tensor(np.float32(3.0))
    np.testing.assert_allclose(g(x, True).numpy(), 6.0)
    np.testing.assert_allclose(g(x, False).numpy(), 3.0)


def test_layer_forward_transformed():
    import paddle_tpu.nn as nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if (h.sum() > 0):
                out = h * 2
            else:
                out = h - 1
            return out

    paddle.seed(0)
    net = Net()
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    eager = Net.forward(net, x)  # untransformed python path (concrete pred)
    net2 = paddle.jit.to_static(net)
    out = net2(x)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(eager.numpy()), rtol=1e-6)


def test_while_with_body_local_temp():
    """Temps assigned only inside the loop body must not break the
    transform (python predicate) or the carry (tensor predicate)."""
    def f(x, n):
        i = 0
        while i < n:
            tmp = x * 2
            x = tmp - x + 1
            i = i + 1
        return x

    g = ast_transform(f)
    x = paddle.to_tensor(np.float32(0.0))
    np.testing.assert_allclose(g(x, 3).numpy(), 3.0)

    def h(x):
        while (x.sum() < 5):
            tmp = x + 1
            x = tmp
        return x

    g2 = ast_transform(h)
    np.testing.assert_allclose(
        g2(paddle.to_tensor(np.array([0.0], np.float32))).numpy(), [5.0])


def test_to_static_redecoration_idempotent():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Linear(4, 4)
    net = paddle.jit.to_static(net)
    net = paddle.jit.to_static(net)  # must not crash
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    assert net(x).shape == [2, 4]


def test_to_static_backward_trains():
    """loss.backward() through a @to_static forward must populate parameter
    grads (paddle to_static-training parity: one tape node spans the whole
    compiled program)."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt

    paddle.seed(0)
    net = paddle.jit.to_static(nn.Sequential(nn.Linear(6, 12), nn.Tanh(),
                                             nn.Linear(12, 6)))
    o = popt.Adam(learning_rate=5e-3, parameters=net.parameters())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 6)).astype(np.float32))
    y = paddle.to_tensor((np.asarray(x.numpy()) * 0.5).astype(np.float32))
    mse = nn.MSELoss()
    losses = []
    for _ in range(15):
        loss = mse(net(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses

    # input gradients flow too
    xg = paddle.to_tensor(rng.standard_normal((4, 6)).astype(np.float32),
                          stop_gradient=False)
    out = net(xg).sum()
    out.backward()
    assert xg.grad is not None and np.isfinite(xg.grad.numpy()).all()


def _fwd_ref_fn(x):
    if (x.sum() > 0):
        y = _helper_late(x)  # noqa: F821 — defined later, at call time
    else:
        y = -x
    return y


def test_forward_reference_resolves():
    """Names defined after decoration must resolve at call time (live
    module globals, not a snapshot)."""
    g = ast_transform(_fwd_ref_fn)
    globals()["_helper_late"] = lambda x: x * 10  # defined AFTER transform
    try:
        x = paddle.to_tensor(np.array([2.0], np.float32))
        np.testing.assert_allclose(g(x).numpy(), [20.0])
    finally:
        del globals()["_helper_late"]
