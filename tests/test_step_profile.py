"""In-step profiling (PR 17): named-region device-time attribution
inside the compiled decode/train programs, plus the zero-sync on-device
telemetry block.

Three tiers:

- canned-fixture parser tests (``tests/fixtures/stepprofile_*``): the
  HLO region/bytes parsers, the trace join, the jvp-wrapper and
  module-suffix resolutions, the byte-weighted naming-drift fallback,
  aux-module exclusion, and the in-step roofline math — all pure
  functions, no device work;
- the ``region-manifest`` lint in both directions (repo clean, seeded
  violations flagged);
- live smoke: an on-demand ``capture_step_profile`` over a real serving
  scheduler, and the load-bearing invariant that flipping
  ``enable_step_telemetry`` never changes a generated token or compiles
  an extra program — at dispatch_depth {0, 2} and tp {1, 2}.
"""

import gzip
import json
import os
import shutil
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.observability.step_profile import (
    REGION_MANIFEST,
    StepProfiler,
    attribute_trace,
    load_trace_events,
    parse_hlo_instruction_bytes,
    parse_hlo_instruction_regions,
    region,
)
from paddle_tpu.serving import ContinuousBatchingScheduler, SchedulerConfig
from tools.graft_lint.regioncheck import check_regions, load_manifest_static

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


@pytest.fixture(autouse=True, scope="module")
def _no_aot_replay():
    """Serving decode programs must compile fresh: XLA:CPU AOT replay
    corrupts their numerics (same fence as test_serving_sched)."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _fixture_hlo() -> str:
    with open(os.path.join(FIXTURES, "stepprofile_module.hlo.txt")) as f:
        return f.read()


def _fixture_events():
    with open(os.path.join(FIXTURES, "stepprofile_trace.json")) as f:
        doc = json.load(f)
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"]


# --------------------------------------------------- HLO parser (canned)

def test_parse_hlo_regions_paths_and_jvp_wrapper():
    module, regions = parse_hlo_instruction_regions(_fixture_hlo())
    assert module == "jit_step"
    # transform wrappers (jvp(rgn_kv_gather)) still count as components
    assert regions["gather.1"] == ("attention", "kv_gather")
    assert regions["dot.1"] == ("attention",)
    assert regions["dot.2"] == ("mlp",)
    assert regions["sort.1"] == ("sampling",)
    # op_name present but no region component -> () = unattributed time
    assert regions["add.1"] == ()
    # no op_name metadata at all -> not in the map
    assert "p0.1" not in regions and "tuple.3" not in regions


def test_parse_hlo_bytes():
    nb = parse_hlo_instruction_bytes(_fixture_hlo())
    assert nb["gather.1"] == 4 * 64 * 4      # f32[4,64]
    assert nb["dot.1"] == 4 * 32 * 4
    assert nb["copy.2"] == 4 * 4             # f32[4]
    assert nb["p0.1"] == 4 * 8 * 4
    assert "tuple.3" not in nb               # tuple-shaped: skipped


# ------------------------------------------------- attribution (canned)

def _fixture_programs():
    module, regions = parse_hlo_instruction_regions(_fixture_hlo())
    nb = parse_hlo_instruction_bytes(_fixture_hlo())
    primary = {"name": "decode", "module": module, "regions": regions,
               "nbytes": nb, "flops": 1.0e6, "bytes_accessed": 2.0e6,
               "primary": True}
    # same-module collision (prefill buckets jit the same function):
    # maps dot.1 to a DIFFERENT region; list order must resolve it to
    # the primary's map
    prefill = {"name": "prefill", "module": module,
               "regions": {"dot.1": ("mlp",)}}
    return [primary, prefill]


def test_attribute_trace_fixture_end_to_end():
    out = attribute_trace(_fixture_events(), _fixture_programs())
    total = 30 + 20 + 25 + 5 + 10 + 12 + 8
    assert out["total_device_time_us"] == pytest.approx(total)
    assert out["unattributed_us"] == pytest.approx(10)      # add.1: ()
    assert out["coverage"] == pytest.approx((total - 10) / total, abs=1e-5)
    # shares sum to coverage, never renormalized to 1
    assert sum(out["region_shares"].values()) == pytest.approx(
        out["coverage"], abs=1e-4)
    rt = out["region_time_us"]
    # innermost wins the leaf: gather.1 (attention/kv_gather) is
    # kv_gather's; copy.7 is naming drift -> byte-weighted fallback over
    # the unmatched copy.* map entries (1024B -> kv_gather, 16B -> mlp)
    assert rt["kv_gather"] == pytest.approx(30 + 12 * 1024 / 1040,
                                            abs=1e-2)
    assert rt["mlp"] == pytest.approx(25 + 12 * 16 / 1040, abs=1e-2)
    # dot.1 resolves against the PRIMARY program's map despite the
    # colliding prefill row, and module "jit_step.1" resolves to
    # "jit_step" via the uniquifier-suffix fallback (20 + 8)
    assert rt["attention"] == pytest.approx(28, abs=1e-2)
    assert rt["sampling"] == pytest.approx(5, abs=1e-2)
    # outermost wins the group share
    assert out["group_shares"]["attention"] == pytest.approx(
        (30 + 20 + 8 + 12 * 1024 / 1040) / total, abs=1e-4)
    # device time in modules owned by no profiled program is reported,
    # not silently dropped — and excluded from the coverage denominator
    assert out["aux_modules"] == {"jit__threefry_split": 100.0}
    prog = out["programs"]["decode"]
    assert prog["events"] == 7
    assert prog["executions"] == 2           # dot.1 ran twice
    assert prog["step_device_time_s"] == pytest.approx(total / 2 * 1e-6)
    assert out["programs"]["prefill"]["events"] == 0


def test_attribute_trace_roofline_decomposition():
    out = attribute_trace(_fixture_events(), _fixture_programs())
    roof = out["decode_roofline"]
    assert roof["program"] == "decode"
    assert roof["flops"] == 1.0e6 and roof["bytes_accessed"] == 2.0e6
    assert 0.0 < roof["bandwidth_util"] <= 1.0
    rs = out["programs"]["decode"]["region_shares"]
    for r, share in rs.items():
        assert roof["region_bytes_est"][r] == int(share * 2.0e6)
        assert roof["bandwidth_util_by_region"][r] == pytest.approx(
            share * roof["bandwidth_util"], abs=1e-5)
    # estimates decompose the measured step: never exceed the whole
    assert sum(roof["region_bytes_est"].values()) <= 2.0e6


def test_load_trace_events_reads_newest_gz(tmp_path):
    d = tmp_path / "plugins" / "profile" / "2026_08_06"
    d.mkdir(parents=True)
    with open(os.path.join(FIXTURES, "stepprofile_trace.json"), "rb") as f:
        raw = f.read()
    with gzip.open(d / "host.trace.json.gz", "wb") as f:
        f.write(raw)
    events = load_trace_events(str(tmp_path))
    assert len(events) == len(_fixture_events())   # complete events only
    assert all(e["ph"] == "X" for e in events)
    assert load_trace_events(str(tmp_path / "empty")) == []


# ------------------------------------------------------- region wrapper

def test_region_rejects_undeclared_name():
    with pytest.raises(ValueError, match="REGION_MANIFEST"):
        with region("not_a_region"):
            pass
    with region("attention"):      # declared: plain scope, no error
        pass


# ------------------------------------------------- region-manifest lint

def test_region_lint_repo_clean():
    root = os.path.join(REPO, "paddle_tpu")
    manifest = load_manifest_static(root)
    # the static (ast) read and the imported manifest must agree
    assert manifest == REGION_MANIFEST
    report = check_regions(root, manifest)
    assert report["ok"], report
    # every manifest entry is annotated somewhere
    assert sorted(report["regions_annotated"]) == sorted(manifest)


def test_region_lint_flags_seeded_violations(tmp_path):
    pkg = tmp_path / "fakepkg"
    (pkg / "observability").mkdir(parents=True)
    (pkg / "observability" / "step_profile.py").write_text(
        'REGION_MANIFEST = {\n'
        '    "used": {"owner": "x", "category": "Forward"},\n'
        '    "stale_one": {"owner": "x", "category": "Forward"},\n'
        '    "bad": {},\n'
        '}\n')
    (pkg / "engine.py").write_text(
        'def f(name):\n'
        '    with region("used"):\n'
        '        pass\n'
        '    with region("bad"):\n'
        '        pass\n'
        '    with region("undeclared_x"):\n'
        '        pass\n'
        '    with region(name):\n'
        '        pass\n')
    report = check_regions(str(pkg), load_manifest_static(str(pkg)))
    assert not report["ok"]
    assert report["undeclared"] == ["undeclared_x"]
    assert report["stale"] == ["stale_one"]
    assert report["malformed_entries"] == ["bad"]
    [dyn] = report["dynamic_sites"]
    assert dyn["arg"] == "name" and dyn["file"].endswith("engine.py")


def test_region_lint_registered_in_graft_lint():
    from tools.graft_lint import ALL_CHECKERS

    rules = [c.rule for c in ALL_CHECKERS]
    assert "region-manifest" in rules and "span-manifest" in rules


# ------------------------------------------------------------ live smoke

def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 120, int(k)) for k in rng.integers(4, 9, n)]


@pytest.fixture(scope="module")
def profiled_sched():
    """One scheduler captured mid-decode — shared by the capture /
    endpoint / postmortem tests (the trace is the expensive part)."""
    paddle.seed(7)
    model = GPTForCausalLM(gpt_tiny(num_layers=1))
    sched = ContinuousBatchingScheduler(model, SchedulerConfig(
        max_num_seqs=2, max_seq_len=64, block_size=8, max_new_tokens=8))
    for p in _prompts(2):
        sched.add_request(p, max_new_tokens=40)
    for _ in range(4):                     # compile + fill the token grid
        sched.step()
    n_before = sched.num_programs()
    summary = sched.capture_step_profile(steps=4)
    n_after = sched.num_programs()
    while sched.has_unfinished():
        sched.step()
    yield sched, summary, (n_before, n_after)
    sched.shutdown()


def test_capture_live_attributes_decode_regions(profiled_sched):
    sched, summary, (n_before, n_after) = profiled_sched
    assert summary["enabled"], summary.get("error")
    assert summary["trace_events"] > 0
    # capture is observation only: zero new compiled programs
    assert n_after == n_before
    shares = summary["region_shares"]
    for r in ("kv_gather", "attention", "mlp", "sampling"):
        assert shares.get(r, 0.0) > 0.0, (r, shares)
    assert sum(shares.values()) == pytest.approx(summary["coverage"],
                                                 abs=1e-3)
    assert summary["coverage"] >= 0.5, summary
    roof = summary.get("decode_roofline")
    assert roof and 0.0 < roof["bandwidth_util"] <= 1.0
    assert roof["bandwidth_util_by_region"]


def test_capture_feeds_endpoint_and_postmortem(profiled_sched):
    sched, summary, _ = profiled_sched
    # postmortem bundles attach the LATEST capture (capture-on-alarm)
    bundle = sched.postmortems.capture("test", "seeded", force=True)
    assert bundle["step_profile"]["coverage"] == summary["coverage"]
    # /debug/stepprofile serves the same state without touching devices
    ep = sched.start_endpoint()
    try:
        idx = json.loads(urllib.request.urlopen(
            f"{ep.url}/debug", timeout=10).read().decode())
        assert "/debug/stepprofile" in idx["routes"]
        doc = json.loads(urllib.request.urlopen(
            f"{ep.url}/debug/stepprofile", timeout=10).read().decode())
        [state] = [v for k, v in doc.items() if k.startswith("scheduler")]
        assert state["telemetry_enabled"] is True
        assert state["last_capture"]["coverage"] == summary["coverage"]
        assert state["telemetry"]["steps"] > 0
    finally:
        ep.stop()


def test_telemetry_snapshot_fields(profiled_sched):
    sched, _, _ = profiled_sched
    snap = sched.telemetry_snapshot()
    assert 0.0 < snap["occupancy"] <= 1.0
    assert snap["kv_blocks"] > 0
    assert 0.0 < snap["mean_max_prob"] <= 1.0
    assert snap["mean_entropy"] >= 0.0
    assert snap["steps"] > 0


def _generate(depth, telemetry, tp=None, seed=7):
    from paddle_tpu.serving.sharded import TensorParallelSharding

    paddle.seed(seed)
    model = GPTForCausalLM(gpt_tiny(num_layers=1))
    sharding = TensorParallelSharding(tp=tp) if tp else None
    sched = ContinuousBatchingScheduler(
        model,
        SchedulerConfig(max_num_seqs=2, max_seq_len=64, block_size=8,
                        dispatch_depth=depth,
                        enable_step_telemetry=telemetry),
        sharding=sharding)
    outs = sched.generate(_prompts(3), max_new_tokens=6)
    n = sched.num_programs()
    sched.shutdown()
    return outs, n


def test_telemetry_token_identity_and_program_count():
    """The tentpole invariant: the telemetry block rides the compiled
    step's existing outputs — switching it off changes neither a token
    nor the compiled-program count, at sync and dispatch-ahead depths."""
    ref, _ = _generate(depth=0, telemetry=True)
    for depth in (0, 2):
        on, n_on = _generate(depth=depth, telemetry=True)
        off, n_off = _generate(depth=depth, telemetry=False)
        assert n_on == n_off
        for a, b, c in zip(ref, on, off):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)


def test_telemetry_token_identity_sharded():
    """Same invariant across the tp mesh: tp in {1, 2} with telemetry
    on/off all decode the identical token streams."""
    ref, _ = _generate(depth=0, telemetry=True)
    for tp in (1, 2):
        on, n_on = _generate(depth=0, telemetry=True, tp=tp)
        off, n_off = _generate(depth=0, telemetry=False, tp=tp)
        assert n_on == n_off
        for a, b, c in zip(ref, on, off):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)


# --------------------------------------------------------- train regions

def test_trainstep_hlo_carries_phase_regions():
    """The compiled TrainStep's op_name metadata carries the
    forward/backward/optimizer group regions (train_bench attributes a
    live trace against exactly this map)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models import (
        GPTConfig,
        GPTPretrainingCriterion,
    )
    from paddle_tpu.observability.program_inventory import (
        get_program_inventory,
    )

    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=32)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    criterion = GPTPretrainingCriterion(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())

    def loss_fn(m, ids, labels):
        return criterion(m(ids), labels)

    step = TrainStep(model, loss_fn, optimizer, nonblocking=True)
    ids = np.ones((2, 8), dtype=np.int32)
    step(ids, ids.copy()).loss_value()
    entry = get_program_inventory().entries(kind="train_step")[-1]
    hlo = get_program_inventory().hlo_text(entry)
    assert hlo
    _, regions = parse_hlo_instruction_regions(hlo)
    groups = {p[0] for p in regions.values() if p}
    assert {"forward", "backward", "optimizer"} <= groups, groups


# --------------------------------------------------- profiler edge cases

def test_step_profiler_capture_error_never_raises():
    def boom():
        raise RuntimeError("step exploded")

    prof = StepProfiler(boom, lambda: [])
    out = prof.capture(steps=1)
    assert out["enabled"] is False
    assert "step exploded" in out["error"]
    assert prof.last_summary == out
    # the process-wide trace lock was released: a second capture runs
    ran = []
    prof2 = StepProfiler(lambda: ran.append(1), lambda: [])
    out2 = prof2.capture(steps=2)
    assert ran == [1, 1]
    assert out2["enabled"] is True and out2["steps_requested"] == 2
