"""r5 inference analysis-pass stack (reference AnalysisConfig::
pass_builder / AnalysisPredictor::OptimizeInferenceProgram): pass listing
and deletion, bf16 weight residency (numerics preserved, applied pass
reported), prewarm compile, donation gate."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference


def _saved_model(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path / "m")
    from paddle_tpu.jit import save
    from paddle_tpu.static import InputSpec

    save(model, path, input_spec=[InputSpec([2, 8], "float32")])
    return model, path


def test_pass_builder_listing_and_delete(tmp_path):
    _, path = _saved_model(tmp_path)
    cfg = inference.Config(path)
    pb = cfg.pass_builder()
    names = pb.all_passes()
    assert "prewarm_compile_pass" in names
    assert "conv_bn_fuse_pass" in names  # absorbed, still listed
    pb.delete_pass("prewarm_compile_pass")
    assert "prewarm_compile_pass" not in pb.all_passes()
    pb.append_pass("prewarm_compile_pass")
    assert pb.all_passes()[-1] == "prewarm_compile_pass"
    assert pb.is_absorbed("fc_fuse_pass")
    assert not pb.is_absorbed("weights_bf16_residency_pass")


def test_prewarm_reported_and_run_works(tmp_path):
    model, path = _saved_model(tmp_path)
    cfg = inference.Config(path)
    pred = inference.create_predictor(cfg)
    assert "prewarm_compile_pass" in pred.applied_passes()
    x = np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32)
    (out,) = pred.run([x])
    ref = np.asarray(model(paddle.to_tensor(x)).numpy())
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_bf16_residency_preserves_numerics(tmp_path):
    model, path = _saved_model(tmp_path)
    x = np.random.default_rng(1).standard_normal((2, 8)).astype(np.float32)
    ref = np.asarray(model(paddle.to_tensor(x)).numpy())

    cfg = inference.Config(path)
    cfg.enable_low_precision("bfloat16")
    pred = inference.create_predictor(cfg)
    assert "weights_bf16_residency_pass" in pred.applied_passes()
    # resident weights ARE bf16
    import jax.numpy as jnp

    float_low = [v for v in pred._layer._state_vals_low
                 if jnp.issubdtype(v.dtype, jnp.floating)]
    assert float_low and all(v.dtype == jnp.bfloat16 for v in float_low)
    (out,) = pred.run([x])
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)  # bf16 noise
    # deleting the pass keeps full precision
    cfg2 = inference.Config(path)
    cfg2.enable_low_precision("bfloat16")
    cfg2.pass_builder().delete_pass("weights_bf16_residency_pass")
    pred2 = inference.create_predictor(cfg2)
    assert "weights_bf16_residency_pass" not in pred2.applied_passes()
    (out2,) = pred2.run([x])
    np.testing.assert_allclose(out2, ref, rtol=1e-5)


def test_memory_optim_gates_donation_pass(tmp_path):
    _, path = _saved_model(tmp_path)
    cfg = inference.Config(path)
    cfg.enable_memory_optim()
    pred = inference.create_predictor(cfg)
    assert "donate_input_buffers_pass" in pred.applied_passes()
    cfg2 = inference.Config(path)
    pred2 = inference.create_predictor(cfg2)
    assert "donate_input_buffers_pass" not in pred2.applied_passes()
    assert "applied" in cfg.summary() or cfg.summary() == ""


def test_dynamic_batch_inputspec_roundtrip(tmp_path):
    """InputSpec([None, 8]) must export a program accepting ANY batch
    (symbolic export dims, the reference's any-batch semantics)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import save
    from paddle_tpu.static import InputSpec

    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 4))
    model.eval()
    path = str(tmp_path / "dyn")
    save(model, path, input_spec=[InputSpec([None, 8], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    for batch in (1, 2, 32):
        x = np.random.default_rng(batch).standard_normal(
            (batch, 8)).astype(np.float32)
        (out,) = pred.run([x])
        ref = np.asarray(model(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_donation_preserves_handle_protocol(tmp_path):
    """Set-handles-once + run() repeatedly must keep working under
    enable_memory_optim (donation only applies to the list-call form)."""
    _, path = _saved_model(tmp_path)
    cfg = inference.Config(path)
    cfg.enable_memory_optim()
    pred = inference.create_predictor(cfg)
    x = np.random.default_rng(0).standard_normal((2, 8)).astype(np.float32)
    h = pred.get_input_handle("x0")
    h.copy_from_cpu(x)
    pred.run()
    out1 = pred.get_output_handle("out0").copy_to_cpu()
    pred.run()  # handle buffers must survive
    out2 = pred.get_output_handle("out0").copy_to_cpu()
    np.testing.assert_allclose(out1, out2)
    # list form: buffers released after run
    (out3,) = pred.run([x])
    assert pred._inputs["x0"]._value is None
    np.testing.assert_allclose(out3, out1, rtol=1e-6)


def test_multi_dynamic_inputspec_export(tmp_path):
    """Two dynamic-dim inputs must share one symbolic scope (r5 review:
    separate scopes crashed export)."""
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import save
    from paddle_tpu.static import InputSpec

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 4)
            self.b = nn.Linear(4, 4)

        def forward(self, x, y):
            return self.a(x) + self.b(y)

    paddle.seed(5)
    m = TwoIn()
    m.eval()
    path = str(tmp_path / "two")
    save(m, path, input_spec=[InputSpec([None, 8], "float32"),
                              InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(path))
    for b in (1, 3):
        x = np.ones((b, 8), np.float32)
        y = np.ones((b, 4), np.float32)
        (out,) = pred.run([x, y])
        assert out.shape == (b, 4)
